"""Layer 3: explicit-state model checking of the serve control plane.

The fleet's three control protocols — router active/standby epoch
arbitration, the rollout canary state machine, and FleetScaler
spawn/retire/drain — are guarded dynamically by chaos drills, which
sample interleavings. This pass explores them EXHAUSTIVELY instead: the
transition rules live as pure functions in ``serve/control.py`` (the
plan-serve extraction pattern), the live actuators call those exact
functions, and this module breadth-first-searches every reachable
state under bounded crash/flake budgets, checking the invariants each
protocol's correctness argument rests on:

* **Router HA** (:func:`explore_router_ha`) — from every reachable
  two-router state (probes in any order, transient probe flakes, a
  bounded number of crash+relaunch events), one settle round of
  probes must leave EXACTLY one active router: a stable dual-active
  pair splits the A/B ledger and admin state; a stable dual-standby
  pair is a lost-request window (no router owns mutable state,
  ``/admin`` mutations land nowhere). Locally, every takeover epoch
  must fence (strictly above everything the taker has seen), epochs
  must never move backwards, and a router must never demote in favor
  of a peer at a strictly LOWER epoch — the flipped-comparison bug
  that hands the fleet to stale state.

* **Rollout canary** (:func:`check_rollout_machine`) — every failure
  edge out of ``canary`` must restore the canary subset, every failure
  edge out of ``promoting`` must restore the WHOLE snapshot (a fleet
  split across weight versions must never be a steady state), terminal
  edges must land in ``idle`` with an outcome, and every non-idle
  state must be able to reach ``idle`` (no wedged rollout).

* **Experiment/capacity interleavings**
  (:func:`explore_experiment_interleavings`) — the one-experiment
  guard (``ab_may_start``) must refuse while a canary owns the replica
  groups, and the capacity hold (``scale_hold_reason``) must pin the
  scaler while versions are mixed or arms are pinned: the
  retire-while-canary interleaving (a scale-down popping the canary
  replica mid-watch) must be unreachable.

* **Fleet rank selection** (:func:`explore_fleet_ranks`) — spawn must
  reuse the LOWEST retired slot (port/heartbeat-slot stability), never
  an active one; retire must pick the highest active rank and refuse
  to take the fleet below one worker.

Everything here is pure-Python and jax-free (the supervisor's
constraint) — the whole pass runs in milliseconds, so both launch
preflights get it for free. Each finding carries the exact event trace
that reaches the bad state, so a seeded protocol bug reads as a repro
script, not a probability.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from distributedpytorch_tpu.analysis import Finding, dedupe
from distributedpytorch_tpu.serve import control

#: Budgets for the HA search: enough nondeterminism to reach every
#: interesting configuration (crash the active, crash the taker-over,
#: relaunch both, flake a probe mid-arbitration) while keeping the
#: state space a few thousand nodes.
HA_MAX_CRASHES = 2
HA_MAX_FLAKES = 2
HA_MAX_DEPTH = 12


# -- router HA ---------------------------------------------------------------
# Router i's state: (role, epoch, peer_epoch_seen, alive). Router 0 is
# the born-active primary, router 1 the born-standby — exactly the pair
# `elastic --router-port P --router-standby-port Q` runs.
_BIRTH = (("active", 0, 0, True), ("standby", 0, 0, True))


def _apply_probe(routers, i: int, decide_fn, *, reachable: bool):
    """Router ``i`` runs one HA exchange against its peer; returns the
    (new_routers, decision) pair. Mirrors serve/router.py ``ha_once``:
    fold the peer's epoch into ``peer_epoch_seen`` when reachable, then
    act on the pure decision."""
    role, epoch, seen, alive = routers[i]
    p_role, p_epoch, _p_seen, p_alive = routers[1 - i]
    reachable = reachable and p_alive
    decision = decide_fn(
        role=role, epoch=epoch, primary=(i == 0), peer_epoch_seen=seen,
        peer_reachable=reachable,
        peer_role=p_role if reachable else None,
        peer_epoch=p_epoch if reachable else 0,
    )
    if reachable:
        seen = max(seen, p_epoch)
    if decision.action == control.HA_TAKE_OVER:
        me = ("active", decision.epoch, seen, alive)
    elif decision.action == control.HA_DEMOTE:
        me = ("standby", decision.epoch, seen, alive)
    elif decision.action == control.HA_SYNC:
        me = (role, decision.epoch, seen, alive)
    else:
        me = (role, decision.epoch, seen, alive)
    out = list(routers)
    out[i] = me
    return tuple(out), decision


def _settle(routers, decide_fn):
    """Three alternating fully-reachable probe rounds — the 'network is
    calm now' closure. A correct arbitration converges to one active
    within a round per router; three rounds is converged-or-never."""
    for i in (0, 1, 0):
        if routers[i][3] and routers[1 - i][3]:
            routers, _ = _apply_probe(routers, i, decide_fn,
                                      reachable=True)
    return routers


def _name(i: int) -> str:
    return "primary" if i == 0 else "standby-born"


def explore_router_ha(
    decide_fn: Optional[Callable] = None,
    *,
    max_crashes: int = HA_MAX_CRASHES,
    max_flakes: int = HA_MAX_FLAKES,
    max_depth: int = HA_MAX_DEPTH,
) -> List[Finding]:
    """BFS over every reachable two-router HA state. ``decide_fn``
    defaults to the live seam (``serve/control.decide_ha``); tests
    inject mutated decision rules to prove the explorer catches them."""
    decide_fn = decide_fn or control.decide_ha
    where = "router-ha protocol"
    findings: List[Finding] = []
    seen_states = set()
    # state: (routers, crashes_left, flakes_left); trace: tuple of strs
    start = (_BIRTH, max_crashes, max_flakes)
    queue = collections.deque([(start, ())])
    seen_states.add(start)

    def emit(rule_suffix: str, message: str, trace) -> None:
        path = " -> ".join(trace) if trace else "initial state"
        findings.append(Finding(
            rule="protocol-ha",
            where=where,
            message=f"{message} [trace: {path}]",
            layer="protocol",
        ))

    while queue:
        (routers, crashes, flakes), trace = queue.popleft()
        if len(trace) >= max_depth:
            continue

        # -- invariant: calm network settles to exactly one active with
        # the highest epoch in the system
        if routers[0][3] and routers[1][3]:
            settled = _settle(routers, decide_fn)
            active = [i for i in (0, 1) if settled[i][0] == "active"]
            if len(active) == 2:
                emit(
                    "dual-active",
                    f"dual-active epochs persist: both routers remain "
                    f"active after a calm settle round (epochs "
                    f"{settled[0][1]} vs {settled[1][1]}) — the A/B "
                    f"ledger and admin state fork",
                    trace,
                )
            elif not active:
                emit(
                    "lost-requests",
                    "lost-request window: both routers settle as "
                    "standby — no router owns mutable state, admin "
                    "mutations and ledger writes land nowhere",
                    trace,
                )

        # -- invariant: a lone survivor must promote itself — a standby
        # that rides out its peer's death serves nothing
        alive = [i for i in (0, 1) if routers[i][3]]
        if len(alive) == 1:
            survivor = routers
            for _ in range(2):
                survivor, _d = _apply_probe(survivor, alive[0],
                                            decide_fn, reachable=False)
            if survivor[alive[0]][0] != "active":
                emit(
                    "lost-requests",
                    f"lost-request window: {_name(alive[0])} stays "
                    f"standby after two missed probes of its dead peer "
                    f"— the fleet has no active router until a human "
                    f"intervenes",
                    trace,
                )

        # -- expand: probes (reachable + flaked), crashes, relaunches
        next_states = []
        for i in (0, 1):
            if not routers[i][3]:
                continue
            before = routers[i]
            after, decision = _apply_probe(routers, i, decide_fn,
                                           reachable=True)
            # fencing + monotonicity hold on EVERY probe transition
            if decision.action == control.HA_TAKE_OVER:
                peer_alive = routers[1 - i][3]
                horizon = max(before[1], before[2],
                              routers[1 - i][1] if peer_alive else 0)
                if decision.epoch <= horizon:
                    emit(
                        "fencing",
                        f"takeover epoch {decision.epoch} does not "
                        f"fence: {_name(i)} takes over at an epoch not "
                        f"strictly above everything it has seen "
                        f"(horizon {horizon}) — a relaunched ex-active "
                        f"could outrank it",
                        trace + (f"{_name(i)} probes peer",),
                    )
            if decision.action == control.HA_DEMOTE and \
                    routers[1 - i][3] and routers[1 - i][1] < before[1]:
                emit(
                    "demote-to-stale",
                    f"{_name(i)} demotes at epoch {before[1]} in favor "
                    f"of a peer at the strictly LOWER epoch "
                    f"{routers[1 - i][1]} — arbitration hands the "
                    f"fleet to stale state (flipped epoch comparison)",
                    trace + (f"{_name(i)} probes peer",),
                )
            if after[i][1] < before[1]:
                emit(
                    "epoch-rollback",
                    f"epoch moved backwards on {_name(i)}: "
                    f"{before[1]} -> {after[i][1]} after a probe — epoch "
                    f"ordering is the whole arbitration",
                    trace + (f"{_name(i)} probes peer",),
                )
            next_states.append(
                ((after, crashes, flakes),
                 trace + (f"{_name(i)} probes peer",))
            )
            if flakes > 0 and routers[1 - i][3]:
                after_f, _ = _apply_probe(routers, i, decide_fn,
                                          reachable=False)
                next_states.append(
                    ((after_f, crashes, flakes - 1),
                     trace + (f"{_name(i)} probe flakes",))
                )
            if crashes > 0:
                crashed = list(routers)
                crashed[i] = (before[0], before[1], before[2], False)
                next_states.append(
                    ((tuple(crashed), crashes - 1, flakes),
                     trace + (f"{_name(i)} crashes",))
                )
        for i in (0, 1):
            if routers[i][3]:
                continue
            relaunched = list(routers)
            relaunched[i] = _BIRTH[i]  # argv role, epoch 0: born again
            next_states.append(
                ((tuple(relaunched), crashes, flakes),
                 trace + (f"{_name(i)} relaunches",))
            )

        for state, new_trace in next_states:
            if state not in seen_states:
                seen_states.add(state)
                queue.append((state, new_trace))
    return dedupe(findings)


# -- rollout canary machine --------------------------------------------------
def check_rollout_machine(
    transition_fn: Optional[Callable] = None,
) -> List[Finding]:
    """Structural invariants of the rollout transition table: failure
    edges restore (canary scope from ``canary``, WHOLE snapshot from
    ``promoting``), terminal edges land in idle with an outcome, and
    every state can reach idle."""
    transition_fn = transition_fn or control.rollout_transition
    where = "rollout-canary protocol"
    findings: List[Finding] = []
    states = (control.ROLLOUT_IDLE, control.ROLLOUT_LOADING,
              control.ROLLOUT_CANARY, control.ROLLOUT_PROMOTING)
    edges: Dict[str, List[Tuple[str, object]]] = {s: [] for s in states}
    for state in states:
        for event in control.ROLLOUT_EVENTS:
            try:
                step = transition_fn(state, event)
            except ValueError:
                continue
            edges[state].append((event, step))
            if step.state == control.ROLLOUT_IDLE and state != step.state \
                    and step.outcome is None:
                findings.append(Finding(
                    rule="protocol-rollout", where=where,
                    message=(
                        f"edge {state}--{event}--> idle carries no "
                        f"outcome — the verdict (/admin/rollout, flight "
                        f"ring) would read as still-running"
                    ),
                    layer="protocol",
                ))
            if step.state != control.ROLLOUT_IDLE and \
                    step.outcome is not None:
                findings.append(Finding(
                    rule="protocol-rollout", where=where,
                    message=(
                        f"edge {state}--{event}--> {step.state} stamps "
                        f"terminal outcome {step.outcome} on a "
                        f"non-terminal state"
                    ),
                    layer="protocol",
                ))
            failure = step.outcome in (control.ROLLOUT_SWAP_FAILED,
                                       control.ROLLOUT_ROLLED_BACK)
            if state == control.ROLLOUT_CANARY and failure and \
                    step.restore != control.RESTORE_CANARY:
                findings.append(Finding(
                    rule="protocol-rollout", where=where,
                    message=(
                        f"edge canary--{event}--> idle restores "
                        f"{step.restore!r}, not the canary subset — a "
                        f"failed canary would keep serving the rejected "
                        f"candidate on the canary replicas"
                    ),
                    layer="protocol",
                ))
            if state == control.ROLLOUT_PROMOTING and failure and \
                    step.restore != control.RESTORE_ALL:
                findings.append(Finding(
                    rule="protocol-rollout", where=where,
                    message=(
                        f"edge promoting--{event}--> idle restores "
                        f"{step.restore!r}, not the whole snapshot — a "
                        f"promote-time crash would leave the fleet "
                        f"split across weight versions as the steady "
                        f"state"
                    ),
                    layer="protocol",
                ))
            if step.outcome == control.ROLLOUT_PROMOTED and \
                    step.restore != control.RESTORE_NONE:
                findings.append(Finding(
                    rule="protocol-rollout", where=where,
                    message=(
                        f"edge {state}--{event}--> idle promotes AND "
                        f"restores {step.restore!r} — a promotion that "
                        f"rolls itself back"
                    ),
                    layer="protocol",
                ))
    # reachability of idle from every state (no wedged rollout)
    for state in states:
        frontier, visited = {state}, {state}
        while frontier:
            nxt = set()
            for s in frontier:
                for _event, step in edges.get(s, []):
                    if step.state not in visited:
                        visited.add(step.state)
                        nxt.add(step.state)
            frontier = nxt
        if control.ROLLOUT_IDLE not in visited:
            findings.append(Finding(
                rule="protocol-rollout", where=where,
                message=(
                    f"state {state!r} cannot reach idle — a rollout "
                    f"entering it wedges forever (readiness stays "
                    f"false, no further rollout can start)"
                ),
                layer="protocol",
            ))
    return dedupe(findings)


# -- experiment x capacity interleavings -------------------------------------
def explore_experiment_interleavings(
    transition_fn: Optional[Callable] = None,
    ab_guard_fn: Optional[Callable] = None,
    hold_fn: Optional[Callable] = None,
) -> List[Finding]:
    """Interleave the rollout machine with A/B starts and scaler steps
    over a small replica fleet; the retire-while-canary and
    A/B-under-canary interleavings must be refused by the pure guards
    the live code consumes."""
    transition_fn = transition_fn or control.rollout_transition
    ab_guard_fn = ab_guard_fn or control.ab_may_start
    hold_fn = hold_fn or control.scale_hold_reason
    where = "experiment-interleaving protocol"
    findings: List[Finding] = []
    # state: (rollout_state, ab_active, replicas); canaries pin mixed
    # versions while in canary/promoting — exactly engine.versions_mixed
    start = (control.ROLLOUT_IDLE, False, 2)
    seen = {start}
    queue = collections.deque([(start, ())])
    while queue:
        (rstate, ab, replicas), trace = queue.popleft()
        if len(trace) >= 8:
            continue
        mixed = rstate in (control.ROLLOUT_CANARY,
                           control.ROLLOUT_PROMOTING)

        # -- A/B start attempt: the guard must refuse while a canary
        # owns the groups or arms cannot be disjoint
        refusal = ab_guard_fn(rollout_state=rstate,
                              replica_groups=replicas)
        if refusal is None and mixed:
            findings.append(Finding(
                rule="protocol-experiment", where=where,
                message=(
                    "ab_may_start admits a sustained A/B while a "
                    "rollout canary owns the replica groups — two "
                    "experiments would fight over the same replicas "
                    f"[trace: {' -> '.join(trace) or 'initial'}]"
                ),
                layer="protocol",
            ))
        if refusal is None and replicas < 2:
            findings.append(Finding(
                rule="protocol-experiment", where=where,
                message=(
                    f"ab_may_start admits an A/B on {replicas} replica "
                    f"group(s) — arms cannot be disjoint "
                    f"[trace: {' -> '.join(trace) or 'initial'}]"
                ),
                layer="protocol",
            ))

        # -- scaler step: the hold rule must pin while pinned/mixed
        hold = hold_fn(ab_pinned=ab, versions_mixed=mixed)
        if hold is None and mixed:
            findings.append(Finding(
                rule="protocol-experiment", where=where,
                message=(
                    "scale_hold_reason lets the scaler act while weight "
                    "versions are mixed — a scale-down would retire the "
                    "canary replica mid-watch (retire-while-canary) "
                    f"[trace: {' -> '.join(trace) or 'initial'}]"
                ),
                layer="protocol",
            ))
        if hold is None and ab:
            findings.append(Finding(
                rule="protocol-experiment", where=where,
                message=(
                    "scale_hold_reason lets the scaler act while "
                    "replica groups are pinned by a sustained A/B "
                    f"[trace: {' -> '.join(trace) or 'initial'}]"
                ),
                layer="protocol",
            ))

        # -- expand
        succ = []
        for event in control.ROLLOUT_EVENTS:
            try:
                step = transition_fn(rstate, event)
            except ValueError:
                continue
            succ.append(((step.state, ab, replicas),
                         f"rollout:{event}"))
        if refusal is None and not ab:
            succ.append(((rstate, True, replicas), "ab:start"))
        if ab:
            succ.append(((rstate, False, replicas), "ab:stop"))
        if hold is None and replicas > 1:
            succ.append(((rstate, ab, replicas - 1), "scale:down"))
        if hold is None and replicas < 3:
            succ.append(((rstate, ab, replicas + 1), "scale:up"))
        for state, label in succ:
            if state not in seen:
                seen.add(state)
                queue.append((state, trace + (label,)))
    return dedupe(findings)


# -- fleet rank selection ----------------------------------------------------
def explore_fleet_ranks(
    spawn_fn: Optional[Callable] = None,
    retire_fn: Optional[Callable] = None,
    *,
    start_workers: int = 2,
    max_slots: int = 5,
    max_depth: int = 8,
) -> List[Finding]:
    """Every spawn/retire sequence over a small fleet: spawn reuses the
    lowest retired slot and never collides with an active rank; retire
    takes the highest active rank and refuses to go below one."""
    spawn_fn = spawn_fn or control.fleet_spawn_rank
    retire_fn = retire_fn or control.fleet_retire_rank
    where = "fleet-elasticity protocol"
    findings: List[Finding] = []
    start = (tuple(range(start_workers)), frozenset())
    seen = {start}
    queue = collections.deque([(start, ())])

    def path(trace) -> str:
        return " -> ".join(trace) if trace else "initial"

    while queue:
        (active, retired), trace = queue.popleft()
        if len(trace) >= max_depth:
            continue
        succ = []
        if len(active) + len(retired) < max_slots or retired:
            rank = spawn_fn(list(active), frozenset(retired))
            if rank in active:
                findings.append(Finding(
                    rule="protocol-fleet", where=where,
                    message=(
                        f"fleet_spawn_rank chose ACTIVE rank {rank} "
                        f"(active {sorted(active)}) — two workers would "
                        f"bind one port/heartbeat slot "
                        f"[trace: {path(trace)}]"
                    ),
                    layer="protocol",
                ))
            elif retired and rank != min(retired):
                findings.append(Finding(
                    rule="protocol-fleet", where=where,
                    message=(
                        f"fleet_spawn_rank chose {rank} over retired "
                        f"slot(s) {sorted(retired)} — the lowest "
                        f"retired slot must be reused first (its port "
                        f"base+R and heartbeat slot come back with it) "
                        f"[trace: {path(trace)}]"
                    ),
                    layer="protocol",
                ))
            elif not retired and rank != len(active):
                findings.append(Finding(
                    rule="protocol-fleet", where=where,
                    message=(
                        f"fleet_spawn_rank appended rank {rank} with "
                        f"{len(active)} slot(s) allocated — fresh ranks "
                        f"must be dense or ports collide/leak "
                        f"[trace: {path(trace)}]"
                    ),
                    layer="protocol",
                ))
            else:
                succ.append((
                    (tuple(sorted(active + (rank,))),
                     frozenset(retired - {rank})),
                    f"spawn:{rank}",
                ))
        rank = retire_fn(list(active))
        if rank is None:
            if len(active) > 1:
                findings.append(Finding(
                    rule="protocol-fleet", where=where,
                    message=(
                        f"fleet_retire_rank refuses with "
                        f"{len(active)} active workers — scale-down "
                        f"wedges above the floor "
                        f"[trace: {path(trace)}]"
                    ),
                    layer="protocol",
                ))
        elif rank not in active:
            findings.append(Finding(
                rule="protocol-fleet", where=where,
                message=(
                    f"fleet_retire_rank chose rank {rank} which is not "
                    f"active ({sorted(active)}) — SIGTERM lands on a "
                    f"dead slot while a live worker keeps serving "
                    f"unrouted [trace: {path(trace)}]"
                ),
                layer="protocol",
            ))
        elif len(active) <= 1:
            findings.append(Finding(
                rule="protocol-fleet", where=where,
                message=(
                    f"fleet_retire_rank retires the LAST worker "
                    f"(rank {rank}) — the fleet scales to zero "
                    f"[trace: {path(trace)}]"
                ),
                layer="protocol",
            ))
        else:
            if rank != max(active):
                findings.append(Finding(
                    rule="protocol-fleet", where=where,
                    message=(
                        f"fleet_retire_rank chose {rank}, not the "
                        f"highest active rank {max(active)} — rank "
                        f"slots fragment and spawn's append rule "
                        f"collides [trace: {path(trace)}]"
                    ),
                    layer="protocol",
                ))
            succ.append((
                (tuple(r for r in active if r != rank),
                 frozenset(retired | {rank})),
                f"retire:{rank}",
            ))
        for state, label in succ:
            if state not in seen:
                seen.add(state)
                queue.append((state, trace + (label,)))

    # the retire actuation order is a declared constant the supervisor
    # comments against; a reorder is a lost-request window
    if tuple(control.FLEET_RETIRE_ORDER) != (
            "eject_from_routers", "drain_inflight", "sigterm"):
        findings.append(Finding(
            rule="protocol-fleet", where=where,
            message=(
                f"FLEET_RETIRE_ORDER is "
                f"{tuple(control.FLEET_RETIRE_ORDER)} — routers must "
                f"stop placing BEFORE the worker process dies, with the "
                f"drain between, or in-flight requests die with it"
            ),
            layer="protocol",
        ))
    return dedupe(findings)


def analyze_protocols() -> List[Finding]:
    """Run every protocol explorer against the live seams — the
    ``protocol`` layer of ``python -m distributedpytorch_tpu
    analyze``."""
    findings: List[Finding] = []
    findings += explore_router_ha()
    findings += check_rollout_machine()
    findings += explore_experiment_interleavings()
    findings += explore_fleet_ranks()
    return dedupe(findings)
