"""dptlint: static distributed-correctness analysis.

Every distributed-correctness property in this repo used to be proven
only by *running* the program: a mis-scheduled ``ppermute`` in the 1F1B
tick program deadlocks the CPU collective rendezvous and is caught by a
300 s pytest-timeout, a silently-degenerated strategy is caught by
grepping optimized HLO, and a rank-divergent collective is caught only
when a real 2-process run hangs. Pipeline schedules and SPMD shard_map
programs have exactly the shape static verification handles well — the
collective sequence is fully determined at trace time — so this package
converts minutes of dynamic detection (or a burned chip window) into a
sub-minute abstract-eval pass.

Two layers, one CLI (``python -m distributedpytorch_tpu analyze``):

* ``analysis/collectives.py`` — the jaxpr collective checker: abstractly
  trace each strategy's train/eval step (no device execution), walk the
  closed jaxpr into ``shard_map``/``pjit``/``scan``/``cond`` subjaxprs,
  extract the ordered collective program, and verify axis binding,
  ppermute bijectivity + tick-program deadlock-freedom, SPMD rank
  uniformity, and each strategy's declared comms contract (the table
  ``tests/test_hlo_collectives.py`` cross-checks against optimized HLO).
* ``analysis/lint.py`` — a project-specific AST lint over the package
  source: nondeterminism under trace, donated-buffer use-after-donation,
  host-sync hazards in the step hot path, and collectives gated on
  ``process_index()`` Python conditionals.

Wired as the ``lint-distributed`` CI job ahead of tier-1, as a chip-window
preflight in ``tools/bench_multi.py`` (a config whose step fails static
checks is poison-marked before spending budget), and as a launch preflight
in ``dist/elastic.py``. Rule catalog: docs/ANALYSIS.md.

This module stays import-light (no jax): ``Finding`` is shared by the
jax-tracing layer and the pure-AST layer, and jax-free callers (the
elastic supervisor) must be able to name rules without paying for a
backend import.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

#: Virtual CPU devices the collective layer's provisioned subprocess
#: needs (DDP_MP's 4×2 mesh). Single source for ``cli`` (self-provision
#: re-exec) and ``preflight`` (pre-provisioned subprocess) — if one
#: provisioned N and the other M, the sentinel would make ``cli.run``
#: trust the wrong mesh and fail as an rc-2 infra error, which both
#: preflight call sites treat as "proceed": the gate would be silently
#: disabled.
MESH_DEVICES = 8

#: Env sentinel marking a process as already provisioned for the
#: analyzer; ``cli.main`` re-execs under ``utils/provision`` unless set.
PROVISIONED_SENTINEL = "DPT_ANALYZE_PROVISIONED"

#: Strategies the jaxpr collective checker covers, and the pipeline
#: schedules that apply to the MP ones. Defined here (not in
#: ``collectives``, which re-exports them as its defaults) so jax-free
#: callers — the elastic supervisor, bench_multi — can gate their
#: preflights on "is this a collective strategy the analyzer owns"
#: without paying for a backend import.
ANALYSIS_STRATEGIES = ("DP", "SP", "TP", "FSDP", "MP", "DDP_MP")
ANALYSIS_SCHEDULES = ("gpipe", "1f1b")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: a rule violation with an actionable one-line
    message and where it was found (a strategy/schedule combo for the
    collective layer, a ``file:line`` for the lint layer)."""

    rule: str
    where: str
    message: str
    layer: str  # "collectives" | "lint"
    count: int = 1  # identical findings collapsed (per-leaf ppermutes)

    @property
    def line(self) -> str:
        mult = f" [x{self.count}]" if self.count > 1 else ""
        return f"dptlint [{self.rule}] {self.where}: {self.message}{mult}"


def dedupe(findings) -> list:
    """Collapse identical (rule, where, message) findings — a tree-typed
    ppermute traces as one eqn per payload leaf per tick and would
    otherwise report the same flipped edge dozens of times."""
    order: list = []
    counts: dict = {}
    for f in findings:
        key = (f.rule, f.where, f.message, f.layer)
        if key in counts:
            counts[key] += 1
        else:
            counts[key] = 1
            order.append(key)
    return [
        Finding(rule=k[0], where=k[1], message=k[2], layer=k[3], count=counts[k])
        for k in order
    ]


class AnalysisEnvironmentError(RuntimeError):
    """The analyzer could not run (wrong device mesh, missing deps) — an
    infrastructure failure, NOT a finding: callers must never poison-mark
    a config or refuse a launch because the analyzer itself broke."""
