"""``python -m distributedpytorch_tpu analyze`` — the dptverify driver.

Runs every static pass, prints one actionable line per finding, and
exits 0 (clean) / 1 (findings) / 2 (analyzer infrastructure failure —
callers must NOT treat this as a finding). ``--json`` writes the
machine-readable report (``-`` = stdout), which the CI job uploads as
an artifact on failure and the bench_multi / elastic preflights parse;
``--sarif`` additionally projects the findings into SARIF 2.1.0 for
CI PR-diff annotation (the JSON report stays canonical).

The passes ride the two coarse layers:

* ``--layer collectives`` (jax, trace-only): the train AND eval comms
  contracts per strategy × schedule (dropped eval psum = finding), the
  serve-variant collective-freedom checks (float/int8/pallas forwards
  must trace with zero collectives), and the donation-safety pass
  (every serve variant lowered through ``serve/engine.serve_jit`` must
  be donation-free at the intent and aliasing tiers).
* ``--layer lint`` (pure AST + pure Python, jax-free): the source
  lint — including suppression hygiene (unknown/stale ``dptlint:
  disable`` comments are themselves findings).
* The control-plane protocol explorer (``analysis/protocol.py`` —
  router HA arbitration, rollout canary machine, experiment/capacity
  interleavings, fleet rank selection, model-checked exhaustively in
  milliseconds) is jax-free and runs under EVERY layer selection, so
  both launch preflights and the cold CI lint job get it for free.

Self-provisioning: the collective layer traces pipeline strategies over
an 8-device virtual CPU mesh, and jax backends initialize once per
process — so unless this process was already provisioned (the
``DPT_ANALYZE_PROVISIONED`` sentinel), the CLI exec-replaces itself via
``utils/provision.reexec_provisioned_cmd``: pinned to CPU, never dialing a
tunneled TPU runtime, zero chip involvement no matter where it's
invoked from (laptop, CI, a bench session holding a chip window).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from typing import List, Optional, Sequence

from distributedpytorch_tpu.analysis import (
    ANALYSIS_SCHEDULES,
    ANALYSIS_STRATEGIES,
    MESH_DEVICES,
    PROVISIONED_SENTINEL as _SENTINEL,
)

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INFRA = 2


def _fingerprint_world(text: str) -> int:
    """0 (off) or >= 2 simulated ranks — a world of 1 has nothing to
    compare, and silently skipping the desync gate while reporting clean
    is exactly the false confidence the gate exists to prevent."""
    n = int(text)
    if n != 0 and n < 2:
        raise argparse.ArgumentTypeError(
            f"--fingerprint-world needs 0 (off) or >= 2 simulated ranks "
            f"to compare, got {n}"
        )
    return n


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m distributedpytorch_tpu analyze",
        description="dptlint: static distributed-correctness analysis "
        "(jaxpr collective checker + SPMD source lint). See "
        "docs/ANALYSIS.md for the rule catalog.",
    )
    ap.add_argument("--strategies", nargs="+",
                    default=list(ANALYSIS_STRATEGIES),
                    help="Strategies to trace (default: all analyzed "
                         "strategies)")
    ap.add_argument("--mesh", nargs="+", default=[], metavar="SPEC",
                    help="Mesh-config specs (DxMxS[@fsdp|sp], parallel/"
                         "mesh.py) to analyze IN ADDITION to "
                         "--strategies — the preflight surface for "
                         "``-t 4x1x2``-style mesh launches; specs with "
                         "a stage axis trace both --schedules and their "
                         "comms contract derives from the sharding "
                         "rules")
    ap.add_argument("--schedules", nargs="+",
                    default=list(ANALYSIS_SCHEDULES),
                    choices=["gpipe", "1f1b"],
                    help="Pipeline schedules for MP/DDP_MP (and "
                         "stage-axis mesh spec) combos")
    ap.add_argument("--layer", choices=["all", "collectives", "lint"],
                    default="all", help="Which analysis layer(s) to run")
    ap.add_argument("--hlo", action="store_true",
                    help="Also verify the optimized-HLO comms contract "
                         "(AOT CPU compile per combo; slower, still zero "
                         "execution)")
    ap.add_argument("--fingerprint-world", type=_fingerprint_world,
                    default=0, metavar="N",
                    help="Trace each combo's train step under N "
                         "simulated process identities and compare the "
                         "ordered-collective fingerprints (the "
                         "multi-process launch preflight's gloo-desync "
                         "gate — catches collectives gated on ranks the "
                         "dual-rank re-trace never simulates); "
                         "0 = off, needs N >= 2")
    ap.add_argument("--fingerprint-snapshot", default=None,
                    choices=["write", "check"],
                    help="Persist ('write') or verify ('check') the "
                         "per-combo ordered-collective fingerprints at "
                         "--snapshot-path: write before a jax upgrade, "
                         "check after — drifted combos flag as rule "
                         "fingerprint-snapshot with both toolchain "
                         "versions named (hybrid --mesh specs are "
                         "fingerprinted too); requires the collectives "
                         "layer")
    ap.add_argument("--snapshot-path", default="dpt_fingerprints.json",
                    metavar="PATH",
                    help="Fingerprint snapshot artifact for "
                         "--fingerprint-snapshot (default: "
                         "dpt_fingerprints.json)")
    ap.add_argument("--plan", default=None, metavar="PATH",
                    help="Check a saved dpt_plan for staleness: re-trace "
                         "every fingerprinted point and flag rows whose "
                         "ordered-collective fingerprint no longer "
                         "matches the current trace (rule stale-plan — "
                         "a drifted plan ranks legs from a program that "
                         "no longer exists); requires the collectives "
                         "layer")
    ap.add_argument("--no-rank-check", action="store_true",
                    help="Skip the simulated-rank re-trace (halves trace "
                         "count; the dual-rank check is what catches "
                         "process_index()-gated collectives at the jaxpr "
                         "level)")
    ap.add_argument("--lint-root", default=None,
                    help="Directory tree for the AST lint (default: the "
                         "installed distributedpytorch_tpu package)")
    ap.add_argument("--json", dest="json_path", default=None,
                    metavar="PATH",
                    help="Write the JSON report here ('-' = stdout; "
                         "findings lines then go to stderr)")
    ap.add_argument("--sarif", dest="sarif_path", default=None,
                    metavar="PATH",
                    help="Also write the findings as SARIF 2.1.0 (for "
                         "CI PR-diff annotation via code-scanning "
                         "upload); the JSON report stays canonical")
    return ap


def run(argv: Optional[Sequence[str]] = None) -> int:
    """The provisioned body: parse, analyze, report."""
    args = build_parser().parse_args(argv)
    if args.fingerprint_world >= 2 and args.layer == "lint":
        # the desync gate lives in the collectives layer; silently
        # skipping a gate the operator explicitly asked for is the
        # false confidence _fingerprint_world exists to prevent
        print("analyze: --fingerprint-world requires the collectives "
              "layer (--layer all|collectives)", file=sys.stderr)
        return EXIT_INFRA
    if args.plan and args.layer == "lint":
        # same contract: the stale-plan re-trace IS a collectives-layer
        # check — skipping it silently would report a drifted plan clean
        print("analyze: --plan requires the collectives layer "
              "(--layer all|collectives)", file=sys.stderr)
        return EXIT_INFRA
    if args.fingerprint_snapshot and args.layer == "lint":
        # same contract again: snapshot write/check trace programs
        print("analyze: --fingerprint-snapshot requires the collectives "
              "layer (--layer all|collectives)", file=sys.stderr)
        return EXIT_INFRA
    t0 = time.monotonic()
    findings: List = []
    combos: List[str] = []
    fingerprints: dict = {}
    serve_variants: List[str] = []
    lint_files = 0
    try:
        if args.layer in ("all", "collectives"):
            from distributedpytorch_tpu.analysis import collectives
            from distributedpytorch_tpu.parallel.mesh import parse_mesh_spec

            for spec in args.mesh:
                try:
                    parse_mesh_spec(spec)  # refuse malformed specs loudly
                except ValueError as exc:
                    # bad invocation, caught BEFORE any combo traces —
                    # a clear message, and no other combo's findings
                    # are ever at stake (unbuildable-but-parseable
                    # specs degrade per combo to a mesh-config finding
                    # inside analyze_combo)
                    print(f"analyze: --mesh {exc}", file=sys.stderr)
                    return EXIT_INFRA
            # order-preserving dedup across (and within) both lists: a
            # repeated method must not trace (and fingerprint) twice —
            # the planner gets this for free from its point de-dup
            strategies = list(
                dict.fromkeys(list(args.strategies) + list(args.mesh))
            )
            cfindings, combos = collectives.analyze(
                strategies=strategies,
                schedules=args.schedules,
                hlo=args.hlo,
                rank_check=not args.no_rank_check,
            )
            findings += cfindings
            if args.fingerprint_world >= 2:
                ffindings, fingerprints = collectives.fingerprint_combos(
                    strategies=strategies,
                    schedules=args.schedules,
                    world=args.fingerprint_world,
                )
                findings += ffindings
            if args.fingerprint_snapshot == "write":
                payload = collectives.write_fingerprint_snapshot(
                    args.snapshot_path,
                    strategies=strategies,
                    schedules=args.schedules,
                )
                print(
                    f"analyze: wrote "
                    f"{len(payload['fingerprints'])} fingerprint(s) "
                    f"(jax {payload['jax']}) to {args.snapshot_path}",
                    file=sys.stderr,
                )
            elif args.fingerprint_snapshot == "check":
                payload = collectives.load_fingerprint_snapshot(
                    args.snapshot_path
                )
                if payload is None:
                    # a missing/corrupt/version-skewed snapshot is a bad
                    # invocation, not a clean check
                    print(f"analyze: --snapshot-path "
                          f"{args.snapshot_path}: not a readable "
                          f"fingerprint snapshot", file=sys.stderr)
                    return EXIT_INFRA
                findings += collectives.check_fingerprint_snapshot(
                    payload
                )
            if args.plan:
                from distributedpytorch_tpu.analysis.planner import (
                    check_plan_staleness,
                    load_plan,
                )

                payload = load_plan(args.plan)
                if payload is None:
                    # a missing/corrupt/version-skewed plan is a bad
                    # invocation, not a clean plan
                    print(f"analyze: --plan {args.plan}: not a readable "
                          f"dpt_plan artifact", file=sys.stderr)
                    return EXIT_INFRA
                findings += check_plan_staleness(payload)
            # serve contracts ride the collectives layer: the traced
            # forwards must be collective-free (and under --hlo the
            # compiled ones too), and every variant must lower
            # donation-free through the engine's one jit wrapper
            sfindings, serve_variants = collectives.analyze_serve(
                hlo=args.hlo
            )
            findings += sfindings
            from distributedpytorch_tpu.analysis import donation

            dfindings, _dtags = donation.analyze_donation()
            findings += dfindings
        if args.layer in ("all", "lint"):
            from distributedpytorch_tpu.analysis import lint

            lfindings, lint_files = lint.lint_package(args.lint_root)
            findings += lfindings
        # the control-plane protocol explorer is jax-free and runs in
        # milliseconds — EVERY layer selection gets it, so the elastic
        # supervisor's collectives-layer preflight and the cold CI lint
        # job both refuse a broken arbitration/rollout/fleet rule
        from distributedpytorch_tpu.analysis import protocol

        findings += protocol.analyze_protocols()
    except Exception as exc:  # noqa: BLE001 — infra failure, distinct rc
        print(f"analyze: infrastructure failure: {type(exc).__name__}: "
              f"{exc}", file=sys.stderr)
        return EXIT_INFRA

    report = {
        "clean": not findings,
        "findings": [dataclasses.asdict(f) for f in findings],
        "combos": combos,
        "fingerprints": fingerprints,
        "serve_variants": serve_variants,
        "protocol": True,
        "lint_files": lint_files,
        "hlo": bool(args.hlo),
        "plan": args.plan,
        "fingerprint_snapshot": args.fingerprint_snapshot,
        "duration_s": round(time.monotonic() - t0, 2),
    }
    out = sys.stderr if args.json_path == "-" else sys.stdout
    for f in findings:
        print(f.line, file=out)
    print(
        f"analyze: {len(findings)} finding(s) over "
        f"{len(combos)} combo(s) + {len(serve_variants)} serve "
        f"variant trace(s) + {lint_files} linted file(s) + the "
        f"protocol explorer in {report['duration_s']}s",
        file=out,
    )
    if args.json_path == "-":
        json.dump(report, sys.stdout, indent=2)
        print()
    elif args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(report, f, indent=2)
    if args.sarif_path:
        from distributedpytorch_tpu.analysis.sarif import write_sarif

        write_sarif(args.sarif_path, findings)
    return EXIT_FINDINGS if findings else EXIT_CLEAN


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[2:] if argv is None else argv)
    if os.environ.get(_SENTINEL) == "1":
        return run(argv)
    from distributedpytorch_tpu.utils.provision import reexec_provisioned_cmd

    # exec-replace, not a child process: the PID CI's `timeout` holds IS
    # the provisioned analyzer, so a timeout kill leaves no orphan still
    # writing the JSON report while the artifact step uploads it
    reexec_provisioned_cmd(
        MESH_DEVICES, _SENTINEL,
        [sys.executable, "-u", "-m", "distributedpytorch_tpu", "analyze",
         *argv],
    )


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
