"""Checkpointing: native full-state save/resume + reference .pth interop.

The reference saves a bare ``state_dict`` once, after the final epoch, and
can only reload weights — no optimizer/scheduler/step state, so no true
resume (reference utils/train_utils.py:88, train.py:42-43; SURVEY.md §5).
This module fixes that:

  * `save_checkpoint` / `load_checkpoint` — the native format: one msgpack
    file holding params, Adam state, plateau-scheduler state, step and epoch
    counters. Written atomically (tmp + rename) so a crash mid-write never
    corrupts the previous checkpoint. Device arrays are gathered to host
    numpy first, so a sharded (DDP / pipeline) run saves exactly once per
    process-0 without layout baggage — restored params can be re-placed
    under any strategy's sharding.
  * `export_reference_pth` / `import_reference_pth` — interop shim keyed to
    the reference's parameter names (``encoder.conv1.conv_block.0.weight``…,
    reference model/unet_parts.py:9-14, 22-26, 46-54, unet_model.py:7-10)
    with NHWC↔NCHW kernel transposes. Import tolerates the DDP ``module.``
    key prefix the reference leaks into its DDP checkpoints (quirk 9).

Resilience (docs/RELIABILITY.md):

  * **multi-host-safe gather** — `_to_host` allgathers each leaf that is
    sharded across processes (FSDP/TP on a pod: not fully addressable, so
    a bare ``device_get`` would fail); the gather is COLLECTIVE, so every
    process must reach the save path (train/loop.py builds the payload on
    all ranks and gates only the file write to rank 0);
  * **integrity footer** — every file carries a sha256 of its msgpack
    payload; restore verifies it and refuses torn/corrupt bytes with
    :class:`CheckpointCorruptError` (legacy footer-less files still load);
  * **retention + fallback** — saves retain the newest ``keep`` files
    (``x.ckpt``, ``x.ckpt.1``, …) and `load_checkpoint` automatically
    falls back to the newest INTACT retained file, so a crash mid-write
    can no longer strand a restart on a corrupt checkpoint.
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import os
import threading
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import flax.serialization
import jax
import numpy as np

from distributedpytorch_tpu.utils import faults

logger = logging.getLogger(__name__)

CKPT_VERSION = 1

# Integrity footer: payload bytes + MAGIC + sha256(payload). Fixed-size
# trailer so the reader can split it off without parsing; files written
# before the footer existed simply lack the MAGIC and skip verification.
_HASH_MAGIC = b"DPT-SHA256:"
_FOOTER_LEN = len(_HASH_MAGIC) + 32


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed its integrity check (hash mismatch or
    unparseable payload) — torn write, bit rot, or truncation."""


def needs_collective_gather(x) -> bool:
    """True for a leaf sharded ACROSS processes (FSDP/TP state on a pod):
    not materializable by any single host, so `_to_host` must allgather
    it — a collective every rank participates in. ONE definition shared
    with the trainer's save gating (train/loop.py `_save_needs_all_ranks`):
    if the two ever disagreed, non-main ranks would skip a payload build
    `_to_host` treats as collective and every rank would hang."""
    return (
        isinstance(x, jax.Array)
        and not x.is_fully_addressable
        and not x.is_fully_replicated
    )


def _to_host(tree):
    # ONE device_get for the whole tree: per-leaf pulls are a synchronous
    # device→host round trip each (~100 ms over a tunneled runtime —
    # ~140 leaves made every checkpoint save cost ~12 s). Leaves sharded
    # ACROSS processes (FSDP/TP state on a pod) are not fully addressable
    # — device_get cannot materialize them — so those are allgathered per
    # leaf instead (a collective: every process must call, in the same
    # leaf order — jax.tree flattening order is deterministic). Fully
    # replicated global arrays keep the cheap device_get path.
    leaves, treedef = jax.tree.flatten(tree)
    needs_gather = needs_collective_gather

    if not any(needs_gather(x) for x in leaves):
        return jax.tree.map(np.asarray, jax.device_get(tree))
    from jax.experimental import multihost_utils

    # one batched device_get for ALL non-gathered leaves (per-leaf pulls
    # would reintroduce the round trips the fast path above exists to
    # avoid); only the genuinely sharded leaves pay a collective each
    plain_idx = [i for i, x in enumerate(leaves) if not needs_gather(x)]
    plain = jax.device_get([leaves[i] for i in plain_idx])
    out: list = list(leaves)
    for i, v in zip(plain_idx, plain):
        out[i] = np.asarray(v)
    for i, x in enumerate(leaves):
        if needs_gather(x):
            out[i] = np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return jax.tree.unflatten(treedef, out)


def save_topology() -> dict:
    """The mesh/sharding topology a checkpoint is being saved under —
    recorded in the manifest so restore can SAY it is resharding
    (N→M processes, different mesh shape) rather than silently assuming
    an identical layout. Restore never *requires* a topology match:
    `_to_host` gathers every leaf to a full host array at save time, so
    the file is layout-free and re-places under any current mesh
    (`Trainer._restore` logs the reshard when the topologies differ)."""
    return {
        "process_count": int(jax.process_count()),
        "device_count": int(jax.device_count()),
    }


def _build_payload(
    params,
    opt_state=None,
    scheduler_state: Optional[dict] = None,
    step: int = 0,
    epoch: int = 0,
    records_state: Optional[dict] = None,
    model_state=None,
    train_meta: Optional[dict] = None,
    topology: Optional[dict] = None,
) -> dict:
    """Snapshot everything to HOST values. This is the only part of a save
    that must run on the trainer thread: device buffers are donated into
    the next dispatched step, so the device_get cannot be deferred."""
    return {
        "version": CKPT_VERSION,
        # saving-time mesh topology (strategy name, mesh axis sizes,
        # process/device counts) — informational manifest for the
        # mesh-resharding restore path; absent in older checkpoints
        "topology": {**save_topology(), **(topology or {})},
        # small scalar trainer state that must survive resume (best val
        # metrics for --save-best, early-stop patience counter) — plain
        # msgpack-able dict, absent in older checkpoints
        "train_meta": train_meta,
        "params": flax.serialization.to_state_dict(_to_host(params)),
        "opt_state": flax.serialization.to_state_dict(_to_host(opt_state))
        if opt_state is not None
        else None,
        "scheduler": scheduler_state,
        "step": int(step),
        "epoch": int(epoch),
        # metric history (LossRecords.state_dict): a resumed run must append
        # to the run's loss curves, not overwrite the pickles with only its
        # post-resume rows
        "records": records_state,
        # non-trainable model collections (BatchNorm running stats) for
        # stateful models; None otherwise
        "model_state": flax.serialization.to_state_dict(_to_host(model_state))
        if model_state is not None
        else None,
    }


_TMP_COUNTER = itertools.count()

# ONE lock around every rotate/rename/prune of a retention chain: the
# chain is shared mutable state between the async writer thread, any
# synchronous save (--sync-checkpoint, tests, tools), and external
# pruning (a lowered --keep-checkpoints). Without it a prune can delete
# the `path.1` slot an in-flight save just rotated its predecessor into
# — exactly the file restore's fallback would need if that save's
# rename then failed. Held only across cheap filesystem metadata ops
# (the payload write itself happens to a unique tmp name outside any
# contention), so serializing here costs nothing measurable.
_RETENTION_LOCK = threading.Lock()


def _rotate_retained(path: str, keep: int) -> None:
    """Shift the retained chain one slot: ``path`` → ``path.1`` → … up to
    ``path.(keep-1)``. ``keep <= 1`` keeps only the live file (no chain)."""
    if keep <= 1 or not os.path.exists(path):
        return
    for i in range(keep - 1, 0, -1):
        src = path if i == 1 else f"{path}.{i - 1}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i}")


def _prune_retained(path: str, keep: int) -> None:
    # bounded scan (not glob): retained suffixes are small ints and a
    # lowered --keep-checkpoints may leave holes above the new limit
    for i in range(max(1, keep), 64):
        stale = f"{path}.{i}"
        if os.path.exists(stale):
            os.remove(stale)


def prune_retained(path: str, keep: int) -> None:
    """Trim ``path``'s retention chain to the newest ``keep`` files —
    the external entry point (tools, a lowered ``--keep-checkpoints``).
    Takes the retention lock, so it can never race an in-flight
    `save_checkpoint_async` write's rotate/rename out from under it
    (tests/test_faults.py races exactly this)."""
    with _RETENTION_LOCK:
        _prune_retained(path, keep)


def retained_checkpoints(path: str) -> List[str]:
    """The retention chain on disk, newest first (``path`` itself, then
    ``path.1``, …) — the restore fallback order."""
    out = [path] if os.path.exists(path) else []
    for i in range(1, 64):
        cand = f"{path}.{i}"
        if os.path.exists(cand):
            out.append(cand)
    return out


def _write_payload(path: str, payload: dict, keep: int = 1) -> str:
    """Serialize + integrity footer + atomic write (tmp + rename: a crash
    mid-write never corrupts the previous checkpoint), rotating the
    retained chain first so the previous file survives as ``path.1``.
    Unique tmp names: queued async saves of the same path must not
    clobber each other's tmp files."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    blob = flax.serialization.msgpack_serialize(payload)
    if faults.fire("ckpt_write", epoch=payload.get("epoch")):
        # Simulate the failure retention exists for: a write that died
        # half-way AND tore the destination (non-atomic filesystem, power
        # loss mid-rename). Rotate like a real save, leave torn bytes at
        # `path`, and raise — restore must fall back to `path.1`.
        with _RETENTION_LOCK:
            _rotate_retained(path, keep)
            with open(path, "wb") as f:
                f.write(blob[: max(1, len(blob) // 2)])
        raise faults.InjectedFault(
            f"injected ckpt_write fault: torn file left at {path}"
        )
    tmp = f"{path}.tmp.{os.getpid()}.{next(_TMP_COUNTER)}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.write(_HASH_MAGIC)
        f.write(hashlib.sha256(blob).digest())
    with _RETENTION_LOCK:
        _rotate_retained(path, keep)
        os.replace(tmp, path)
        _prune_retained(path, keep)
    return path


def _read_verified(path: str) -> dict:
    """Read + integrity-check one checkpoint file. Hash mismatch and
    unparseable payloads (torn legacy files) both raise
    :class:`CheckpointCorruptError`; footer-less legacy files load
    unverified."""
    with open(path, "rb") as f:
        blob = f.read()
    if (
        len(blob) > _FOOTER_LEN
        and blob[-_FOOTER_LEN:-32] == _HASH_MAGIC
    ):
        body, digest = blob[:-_FOOTER_LEN], blob[-32:]
        if hashlib.sha256(body).digest() != digest:
            raise CheckpointCorruptError(
                f"{path}: content hash mismatch (torn write or bit rot)"
            )
        blob = body
    try:
        return flax.serialization.msgpack_restore(blob)
    except Exception as exc:
        raise CheckpointCorruptError(f"{path}: unreadable payload: {exc}") from exc


def verify_checkpoint(path: str) -> bool:
    """True iff ``path`` parses and (when a footer is present) its hash
    verifies."""
    try:
        _read_verified(path)
        return True
    except CheckpointCorruptError:
        return False


def save_checkpoint(
    path: str,
    params,
    opt_state=None,
    scheduler_state: Optional[dict] = None,
    step: int = 0,
    epoch: int = 0,
    records_state: Optional[dict] = None,
    model_state=None,
    train_meta: Optional[dict] = None,
    keep: int = 1,
    write: bool = True,
    topology: Optional[dict] = None,
) -> None:
    """``write=False`` builds the payload WITHOUT touching disk — the
    multi-process contract: the host snapshot inside `_build_payload` is
    collective when state is sharded across processes, so every rank
    calls this and only rank 0 passes ``write=True`` (train/loop.py)."""
    payload = _build_payload(
        params,
        opt_state,
        scheduler_state,
        step,
        epoch,
        records_state,
        model_state,
        train_meta,
        topology,
    )
    if write:
        _write_payload(path, payload, keep=keep)


# ---------------------------------------------------------------------------
# Async saves: ONE background writer thread, saves applied in submission
# order (so <tag>.ckpt always ends at the newest queued snapshot). The
# thread is a daemon started on first use: serialization + disk I/O are the
# multi-second part of a save (the device_get is not — see _build_payload)
# and nothing in the step loop depends on them.
# ---------------------------------------------------------------------------

_writer_lock = threading.Lock()
_writer_queue = None  # created lazily; holds (Future, path, payload)


def _writer_loop(q):
    while True:
        fut, path, payload, keep = q.get()
        if not fut.set_running_or_notify_cancel():
            continue
        try:
            fut.set_result(_write_payload(path, payload, keep=keep))
        except BaseException as exc:  # surfaced via Future.result()
            fut.set_exception(exc)


def save_checkpoint_async(
    path: str,
    params,
    opt_state=None,
    scheduler_state: Optional[dict] = None,
    step: int = 0,
    epoch: int = 0,
    records_state: Optional[dict] = None,
    model_state=None,
    train_meta: Optional[dict] = None,
    keep: int = 1,
    write: bool = True,
    topology: Optional[dict] = None,
) -> Optional[Future]:
    """`save_checkpoint` with the serialize+write half on the background
    writer: snapshots state to host NOW (cheap single device_get — also
    the correctness boundary, the next step donates these buffers, AND
    the collective boundary: a cross-process allgather must run on the
    caller thread in rank-lockstep, never on the writer), returns a
    Future that resolves to ``path`` when the file is durably in place.
    ``write=False`` (non-main ranks) participates in the snapshot and
    returns None. Callers must eventually ``result()`` the future (the
    trainer drains its list when training ends) or a failed write would
    pass silently.
    """
    global _writer_queue
    payload = _build_payload(
        params,
        opt_state,
        scheduler_state,
        step,
        epoch,
        records_state,
        model_state,
        train_meta,
        topology,
    )
    if not write:
        return None
    with _writer_lock:
        if _writer_queue is None:
            import queue as queue_mod

            _writer_queue = queue_mod.Queue()
            threading.Thread(
                target=_writer_loop,
                args=(_writer_queue,),
                daemon=True,
                name="dpt-ckpt-writer",
            ).start()
    fut: Future = Future()
    _writer_queue.put((fut, path, payload, keep))
    return fut


def resolve_checkpoint(name: str, checkpoint_dir: str = "./checkpoints") -> str:
    """Resolve a checkpoint reference to an existing file path.

    Accepts an explicit path (``./ckpts/run.ckpt``), a bare method name
    (``DP`` → ``<dir>/DP.ckpt``, falling back to ``<dir>/DP.pth``), or an
    extension-suffixed name (``DP.pth`` → resolved inside `checkpoint_dir`,
    matching the trainer's ``-c``/-l`` semantics, train/loop.py). Raises
    FileNotFoundError naming the primary candidate when nothing exists.
    """
    if os.path.isfile(name):  # isfile: a same-named DIRECTORY must not shadow
        return name
    base, explicit_ext = name, None
    for ext in (".ckpt", ".pth"):
        if base.endswith(ext):
            base, explicit_ext = base[: -len(ext)], ext
            break
    # an explicitly-suffixed name tries ONLY that format — 'DP.pth' must
    # never silently load DP.ckpt when both exist
    exts = (explicit_ext,) if explicit_ext else (".ckpt", ".pth")
    for ext in exts:
        cand = os.path.join(checkpoint_dir, f"{base}{ext}")
        if os.path.isfile(cand):
            return cand
        if ext == ".ckpt" and retained_checkpoints(cand):
            # live slot empty but the retention chain survives (a crash
            # between rotate and rename): resolvable — load_checkpoint's
            # fallback walks the chain from the primary path
            return cand
    raise FileNotFoundError(os.path.join(checkpoint_dir, f"{base}{exts[0]}"))


def read_payload(path: str, fallback: bool = True) -> dict:
    """The newest INTACT candidate's raw payload dict (retention-chain
    walk + integrity check — exactly `load_checkpoint`'s file selection,
    WITHOUT binding any target structures). The restore path reads this
    once, inspects the manifest to build policy-correct targets, then
    hands the same payload back to `load_checkpoint` — a multi-GB file
    must not be read and deserialized twice per resume."""
    candidates = retained_checkpoints(path) if fallback else [path]
    if not candidates:
        candidates = [path]
    payload = None
    for cand in candidates:
        try:
            payload = _read_verified(cand)
            if cand != path:
                logger.warning(
                    "checkpoint %s is corrupt or missing — restored the "
                    "newest intact retained file %s instead",
                    path, cand,
                )
            break
        except CheckpointCorruptError as exc:
            logger.warning("checkpoint integrity failure: %s", exc)
    if payload is None:
        raise CheckpointCorruptError(
            f"no intact checkpoint among {candidates} — every candidate "
            "failed its integrity check"
        )
    return payload


def peek_topology(path: str, fallback: bool = True) -> Optional[dict]:
    """The saving-time topology manifest (strategy/mesh/process counts and
    the ``precision`` policy name) of the checkpoint `load_checkpoint`
    would restore — WITHOUT building any target structures. None for
    pre-manifest checkpoints (and raises what `load_checkpoint` would
    raise when no intact candidate exists)."""
    return read_payload(path, fallback=fallback).get("topology")


def load_weights(path: str, params_template):
    """Params from either checkpoint format: native full-state ``.ckpt`` or
    reference ``.pth`` (NHWC↔NCHW transposes, ``module.`` prefix tolerated).
    The format rule lives here only — trainer resume and inference share it."""
    if path.endswith(".pth"):
        return import_reference_pth(path, params_template)
    return load_checkpoint(path, params_template, None)["params"]


def load_checkpoint(
    path: str,
    params_target,
    opt_state_target=None,
    model_state_target=None,
    fallback: bool = True,
    payload: Optional[dict] = None,
) -> Dict[str, Any]:
    """Restore a checkpoint into the given target structures.

    Every file is integrity-checked (`_read_verified`); when ``path``
    itself is corrupt and ``fallback`` is on, restore walks the retention
    chain (``path.1``, ``path.2``, …) to the newest INTACT file — so a
    crash mid-write costs one save interval of progress, not the run
    (`fit_with_restarts` then resumes from the fallback's epoch). All
    candidates corrupt raises :class:`CheckpointCorruptError`.

    ``payload`` short-circuits the file read: a caller that already ran
    `read_payload` (the trainer's policy-aware restore peeks the
    manifest to build its targets) binds against that dict instead of
    reading and deserializing the file a second time.

    Returns ``{'params', 'opt_state', 'scheduler', 'step', 'epoch',
    'records', 'model_state'}``; `opt_state` is None when the checkpoint
    predates it or no target given, `records` (metric history) and
    `model_state` (BatchNorm stats) likewise.
    """
    if payload is None:
        payload = read_payload(path, fallback=fallback)
    out = {
        "params": flax.serialization.from_state_dict(params_target, payload["params"]),
        "opt_state": None,
        "scheduler": payload.get("scheduler"),
        "step": int(payload.get("step", 0)),
        "epoch": int(payload.get("epoch", 0)),
        "records": payload.get("records"),
        "model_state": None,
        "train_meta": payload.get("train_meta"),
        # saving-time mesh topology (None for pre-elastic checkpoints):
        # the restore side compares it against the CURRENT topology and
        # reports a resharding restore (train/loop.py `_restore`)
        "topology": payload.get("topology"),
    }
    if payload.get("opt_state") is not None and opt_state_target is not None:
        out["opt_state"] = flax.serialization.from_state_dict(
            opt_state_target, payload["opt_state"]
        )
    if payload.get("model_state") is not None and model_state_target is not None:
        out["model_state"] = flax.serialization.from_state_dict(
            model_state_target, payload["model_state"]
        )
    return out


# ---------------------------------------------------------------------------
# Reference .pth interop
# ---------------------------------------------------------------------------

# (flax module path) -> (reference state_dict stem). conv1/conv2 inside a
# ConvBlock map to Sequential indices 0/2 (reference unet_parts.py:9-14).
_BLOCK_MAPS: Tuple[Tuple[Tuple[str, ...], str], ...] = tuple(
    [(("encoder", f"block{i}"), f"encoder.conv{i}") for i in range(1, 5)]
    + [(("mid",), "mid")]
    + [(("decoder", f"block{i}"), f"decoder.conv{i}") for i in range(1, 5)]
)


def _flatten_params(params) -> Dict[Tuple[str, ...], np.ndarray]:
    flat = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(prefix + (k,), v)
        else:
            flat[prefix] = np.asarray(jax.device_get(node))

    walk((), flax.serialization.to_state_dict(params))
    return flat


def _kernel_to_torch(arr: np.ndarray, transposed: bool) -> np.ndarray:
    """flax (kh, kw, I, O) → torch conv (O, I, kh, kw) / ConvTranspose
    (I, O, kh, kw) with a spatial flip — lax.conv_transpose correlates with
    the mirrored kernel relative to torch's scatter semantics (validated
    against torch numerics in tests/test_checkpoint.py)."""
    if transposed:
        return arr[::-1, ::-1].transpose(2, 3, 0, 1)
    return arr.transpose(3, 2, 0, 1)


def _kernel_from_torch(arr: np.ndarray, transposed: bool) -> np.ndarray:
    if transposed:
        return arr.transpose(2, 3, 0, 1)[::-1, ::-1]
    return arr.transpose(2, 3, 1, 0)


def _rebuild_from_named(target, name_map, cleaned, transform):
    """Rebuild a pytree shaped like ``target`` by looking each flat path up
    in ``cleaned`` via ``name_map`` and applying ``transform(path, arr)``.
    Shared by both .pth families (reference course model / milesial)."""
    flat = {}
    for path in _flatten_params(target):
        flat[path] = np.ascontiguousarray(transform(path, cleaned[name_map[path]]))

    def walk(prefix, node):
        if isinstance(node, dict):
            return {k: walk(prefix + (k,), v) for k, v in node.items()}
        return flat[prefix]

    as_dict = walk((), flax.serialization.to_state_dict(target))
    return flax.serialization.from_state_dict(target, as_dict)


def _save_pth(state_dict: Dict[str, np.ndarray], path: str) -> None:
    import torch

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    torch.save({k: torch.from_numpy(v.copy()) for k, v in state_dict.items()}, path)


def _load_pth(path: str) -> Dict[str, np.ndarray]:
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    return {k: v.numpy() for k, v in sd.items() if hasattr(v, "numpy")}


def _strip_module_prefix(state_dict: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """DDP saves ``module.``-prefixed keys (reference quirk 9)."""
    return {
        (k[len("module.") :] if k.startswith("module.") else k): np.asarray(v)
        for k, v in state_dict.items()
    }


def _name_map() -> Dict[Tuple[str, ...], str]:
    """flax param path → reference tensor name."""
    m: Dict[Tuple[str, ...], str] = {}
    for flax_path, ref_stem in _BLOCK_MAPS:
        for conv, seq_idx in (("conv1", 0), ("conv2", 2)):
            m[flax_path + (conv, "kernel")] = f"{ref_stem}.conv_block.{seq_idx}.weight"
            m[flax_path + (conv, "bias")] = f"{ref_stem}.conv_block.{seq_idx}.bias"
    for i in range(1, 5):
        m[("decoder", f"upconv{i}", "kernel")] = f"decoder.deconv{i}.weight"
        m[("decoder", f"upconv{i}", "bias")] = f"decoder.deconv{i}.bias"
    m[("segmap", "kernel")] = "segmap.weight"
    m[("segmap", "bias")] = "segmap.bias"
    return m


def _ref_is_transposed(path: Tuple[str, ...]) -> bool:
    return "upconv" in path[-2]


def export_reference_state_dict(params) -> Dict[str, np.ndarray]:
    """flax params (NHWC kernels) → reference-named dict (NCHW layouts,
    see _kernel_to_torch)."""
    names = _name_map()
    out: Dict[str, np.ndarray] = {}
    for path, arr in _flatten_params(params).items():
        if path[-1] == "kernel":
            arr = _kernel_to_torch(arr, _ref_is_transposed(path))
        out[names[path]] = np.ascontiguousarray(arr)
    return out


def import_reference_state_dict(
    state_dict: Dict[str, np.ndarray], params_target
):
    """Reference-named (possibly ``module.``-prefixed, quirk 9) dict → flax
    params shaped like `params_target`."""

    def transform(path, arr):
        if path[-1] == "kernel":
            return _kernel_from_torch(arr, _ref_is_transposed(path))
        return arr

    return _rebuild_from_named(
        params_target, _name_map(), _strip_module_prefix(state_dict), transform
    )


def export_reference_pth(params, path: str) -> None:
    """Write a real torch ``.pth`` loadable by the reference's
    ``model.load_state_dict(torch.load(...))`` (reference train.py:43)."""
    _save_pth(export_reference_state_dict(params), path)


def import_reference_pth(path: str, params_target):
    return import_reference_state_dict(_load_pth(path), params_target)


# ---------------------------------------------------------------------------
# milesial/Pytorch-UNet .pth interop (the public upstream family)
# ---------------------------------------------------------------------------
#
# torch module layout (milesial/Pytorch-UNet unet_parts.py): DoubleConv =
# Sequential(Conv2d, BatchNorm2d, ReLU, Conv2d, BatchNorm2d, ReLU) →
# tensor stems double_conv.{0,1,3,4}; Down wraps it as maxpool_conv.1;
# Up holds `up` (ConvTranspose2d) + `conv` (DoubleConv); OutConv holds
# `conv`. Checkpoints published by that repo load here directly — the
# strongest migration path for its users.


def _milesial_maps(n_levels: int):
    """(flax params path → torch name, flax batch_stats path → torch name)
    for a milesial model with ``n_levels`` width entries (stem + n−1 downs).
    """
    pmap: Dict[Tuple[str, ...], str] = {}
    smap: Dict[Tuple[str, ...], str] = {}

    def double_conv(flax_prefix: Tuple[str, ...], torch_stem: str):
        for conv, bn, c_idx, b_idx in (("conv1", "bn1", 0, 1), ("conv2", "bn2", 3, 4)):
            pmap[flax_prefix + (conv, "kernel")] = f"{torch_stem}.{c_idx}.weight"
            pmap[flax_prefix + (bn, "scale")] = f"{torch_stem}.{b_idx}.weight"
            pmap[flax_prefix + (bn, "bias")] = f"{torch_stem}.{b_idx}.bias"
            smap[flax_prefix + (bn, "mean")] = f"{torch_stem}.{b_idx}.running_mean"
            smap[flax_prefix + (bn, "var")] = f"{torch_stem}.{b_idx}.running_var"

    double_conv(("inc",), "inc.double_conv")
    for i in range(1, n_levels):
        double_conv((f"down{i}", "conv"), f"down{i}.maxpool_conv.1.double_conv")
    for i in range(1, n_levels):
        pmap[(f"up{i}", "up", "kernel")] = f"up{i}.up.weight"
        pmap[(f"up{i}", "up", "bias")] = f"up{i}.up.bias"
        double_conv((f"up{i}", "conv"), f"up{i}.conv.double_conv")
    pmap[("outc", "kernel")] = "outc.conv.weight"
    pmap[("outc", "bias")] = "outc.conv.bias"
    return pmap, smap


def _milesial_levels(params) -> int:
    as_dict = flax.serialization.to_state_dict(params)
    return 1 + sum(1 for k in as_dict if k.startswith("down"))


def export_milesial_state_dict(params, batch_stats) -> Dict[str, np.ndarray]:
    """flax milesial variables → torch-named state dict (NCHW layouts via
    _kernel_to_torch; ``num_batches_tracked`` zeros included so torch's
    strict ``load_state_dict`` accepts it)."""
    pmap, smap = _milesial_maps(_milesial_levels(params))
    out: Dict[str, np.ndarray] = {}
    for path, arr in _flatten_params(params).items():
        if path[-1] == "kernel":
            arr = _kernel_to_torch(arr, transposed=path[-2] == "up")
        out[pmap[path]] = np.ascontiguousarray(arr)
    for path, arr in _flatten_params(batch_stats).items():
        out[smap[path]] = np.ascontiguousarray(arr)
        out[smap[path].rsplit(".", 1)[0] + ".num_batches_tracked"] = np.asarray(
            0, np.int64
        )
    return out


def import_milesial_state_dict(
    state_dict: Dict[str, np.ndarray], params_target, stats_target
):
    """torch-named milesial dict → (params, batch_stats) shaped like the
    given targets. Accepts DDP's ``module.`` prefix like the UNet path."""
    cleaned = _strip_module_prefix(state_dict)
    pmap, smap = _milesial_maps(_milesial_levels(params_target))

    def p_transform(path, arr):
        if path[-1] == "kernel":
            return _kernel_from_torch(arr, transposed=path[-2] == "up")
        return arr

    return (
        _rebuild_from_named(params_target, pmap, cleaned, p_transform),
        _rebuild_from_named(stats_target, smap, cleaned, lambda path, arr: arr),
    )


def export_milesial_pth(params, batch_stats, path: str) -> None:
    _save_pth(export_milesial_state_dict(params, batch_stats), path)


def import_milesial_pth(path: str, params_target, stats_target):
    return import_milesial_state_dict(
        _load_pth(path), params_target, stats_target
    )
