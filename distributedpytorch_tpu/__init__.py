"""distributedpytorch_tpu — a TPU-native (JAX/XLA/pjit) distributed training framework.

A from-scratch, idiomatic-JAX rebuild of the capabilities of the reference
``notnitsuj/DistributedPyTorch`` project (see SURVEY.md): UNet image
segmentation trained under selectable parallelism strategies — single device,
single-process data parallel (DP), multi-process data parallel with gradient
all-reduce over ICI (DDP), a 2-stage microbatched pipeline (MP), and a
DDP×Pipe hybrid on a 2-D device mesh.

Design stance (SURVEY.md §7): ONE functional trainer parameterized by a
strategy (mesh + shardings), not N copy-pasted training loops; NHWC layouts
internally for TPU; XLA collectives (psum / sharding-propagated AllReduce)
instead of NCCL; explicit GPipe schedule instead of async CUDA launches.
"""

__version__ = "0.1.0"

from distributedpytorch_tpu.config import TrainConfig  # noqa: F401
