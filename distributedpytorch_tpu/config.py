"""Run configuration.

Replaces the reference's argparse constants + hardcoded paths
(reference train.py:15-31, utils/train_utils.py:19-20, 26) with one dataclass.
Field defaults mirror the reference CLI defaults (reference train.py:18-24).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class TrainConfig:
    # -- strategy -----------------------------------------------------------
    # A legacy strategy name — "singleGPU" (kept for CLI parity;
    # single-device), "DP", "DDP", "MP", "DDP_MP", "SP" / "DDP_SP",
    # "TP", "FSDP" — or a mesh spec "DxMxS[@fsdp|sp]" naming an
    # arbitrary point on the N-D ('data','model','stage') mesh
    # (parallel/mesh.py; docs/DISTRIBUTED.md "The mesh engine"):
    # e.g. "4x1x2" (data x pipeline), "2x2x1" (data x tensor),
    # "2x2x1@fsdp" (FSDP x tensor), "1x4x1@sp" (spatial). The legacy
    # names are aliases into the same mesh-rule engine — each resolves
    # to its mesh config at strategy construction and reproduces
    # bit-identically as the equivalent spec.
    train_method: str = "singleGPU"

    # -- optimization (reference train.py:18-24 defaults) -------------------
    epochs: int = 10
    learning_rate: float = 1e-4
    batch_size: int = 4
    val_percent: float = 10.0  # percent, divided by 100 like train_utils.py:35
    seed: int = 42
    weight_decay: float = 1e-8  # Adam L2, reference train_utils.py:45

    # Reference quirk 1 (SURVEY.md §2): `(batch_size * loss).backward()` while
    # recording the unscaled loss. Reproduced by default for curve parity.
    faithful_loss_scaling: bool = True
    # Reference quirk 2: DDP multiplies lr by world_size (train_utils.py:199).
    ddp_lr_world_size_scaling: bool = True

    # -- LR schedule: ReduceLROnPlateau(mode='min', patience=2) -------------
    plateau_patience: int = 2
    plateau_factor: float = 0.1

    # -- data ---------------------------------------------------------------
    data_dir: str = "./data"
    images_subdir: str = "train_hq"
    masks_subdir: str = "train_masks"
    # (W, H) like the reference's `newsize=[960,640]` (train_utils.py:26);
    # preprocess reads it as (newW, newH) (dataloading.py:29).
    image_size: Tuple[int, int] = (960, 640)
    num_workers: int = 0  # host-side prefetch threads (0 = synchronous)
    # Device-placement prefetch depth: host→device transfer of batch i+1..i+k
    # overlaps the device's compute of batch i (transfers are comparable to
    # the step time on tunneled/remote runtimes). Applies to K-stacked
    # dispatch payloads too (the whole stack/place pipeline runs on the
    # worker, see utils/prefetch.pipelined_placement). 0 = place
    # synchronously (the bitwise-identical baseline the equivalence tests
    # compare against).
    prefetch_batches: int = 2
    # Epoch-persistent decoded-sample cache budget (data/dataset.SampleCache,
    # MiB of host RAM): epochs >= 2 serve whatever fit from memory instead of
    # re-running PIL/libjpeg decode on identical files every epoch. Shared by
    # the train and val loaders. 0 disables. No eviction — see SampleCache.
    host_cache_mb: int = 1024

    # -- pipeline (MP) ------------------------------------------------------
    num_microbatches: int = 2  # reference hardcodes 2 (unet_model.py:25)
    # Stages in the GPipe schedule. 2 = the reference's encoder|decoder cut
    # (unet_model.py:16-20); any S up to the model's 2L+1 segments works —
    # the bubble is (S−1)/(M+S−1), so raise num_microbatches with S.
    num_stages: int = 2
    # Where stages begin, as model-segment indices (see UNet.apply_segment:
    # L encoder levels, mid, L decoder levels+head). None = the faithful
    # 2-stage cut for S=2, an even split otherwise.
    pipeline_cuts: Optional[Tuple[int, ...]] = None
    # Pipeline schedule (parallel/pipeline.py):
    #   "gpipe" — fill-drain, differentiated through the shard_map; peak
    #             activation memory grows linearly with num_microbatches
    #             (every microbatch's stage activations stay live until
    #             the backward drains);
    #   "1f1b"  — PipeDream-flush: explicit per-tick vjp backward with at
    #             most ~S in-flight microbatches per stage, so peak
    #             activation memory is bounded by the stage count and M
    #             becomes a free throughput lever (the M=8/16 rows that
    #             OOM or remat under gpipe at batch 4). Grad-equivalent
    #             to gpipe (tests/test_pipeline_1f1b.py).
    # Default gpipe until the on-chip A/B lands (tools/bench_pipeline.py
    # --schedule sweep / bench_multi pipeline config).
    pipeline_schedule: str = "gpipe"

    # -- precision (ops/precision.py, docs/PERFORMANCE.md "Precision") ------
    # The mixed-precision policy, --dtype:
    #   "f32"         pure-float32 reference (what equivalence bands are
    #                 measured against);
    #   "bf16"        bf16 conv/activation compute on the MXU, f32 params
    #                 and loss — the shipping default, now explicit;
    #   "bf16_params" bf16 compute AND bf16 on-device params (halved param
    #                 bytes + FSDP all-gather traffic) with f32 master
    #                 weights living in optimizer state (Micikevicius et
    #                 al.'s recipe). Loss/Dice accumulation, wgrad
    #                 accumulation, and the schedule-closing grad psums
    #                 stay f32 under EVERY policy (the stated contracts,
    #                 precision.LOSS_DTYPE/WGRAD_DTYPE/REDUCE_DTYPE).
    dtype: str = "bf16"
    # Legacy compute-dtype override (pre-policy tests/benches pass
    # compute_dtype="float32" for exact comparisons): None = the policy's
    # own compute dtype; a dtype name overrides conv/activation compute
    # only — param storage and master weights still follow `dtype`.
    compute_dtype: Optional[str] = None

    # -- model --------------------------------------------------------------
    # "unet" = the reference course model (7,760,097 params); "milesial" =
    # the original milesial/Pytorch-UNet it derives from (31,037,698 params
    # at n_classes=2; BatchNorm → stateful training, SyncBN-by-construction
    # under data-parallel meshes; reference model/modelsummary.txt:150-247).
    model_arch: str = "unet"
    # None = the architecture's documented channel plan. Narrower tuples
    # build faster-compiling variants for tests.
    model_widths: Optional[Tuple[int, ...]] = None
    # Shallow levels executed in the space-to-depth domain (ops/s2d.py):
    # exactly equivalent numerics, measured ~1.9× step-time win on TPU v5e at
    # the reference config (the full-res C=32/64 convs starve the 128-lane
    # MXU; their s2d forms don't). -1 = auto: 2 on a TPU backend, 0 elsewhere
    # (the rewrite's 4× nominal MACs only pay off on the MXU).
    # 0 = plain pixel-domain execution. Explicit 3 is supported and proven
    # exact (tests/test_s2d.py level-3 cases, both model families) — a
    # re-measure lever for geometries where level 3 still starves the MXU;
    # auto stays at 2 (level 3 regressed at the reference geometry,
    # docs/PERFORMANCE.md).
    s2d_levels: int = -1
    # Compute the s2d 3×3 convs' weight gradients as 9 tap matmuls
    # (ops/conv_backward.py) instead of XLA's conv-backward-filter —
    # identical numerics (tests/test_s2d.py), different schedule. The
    # round-3 step was backward-dominated; this is the A/B lever.
    wgrad_taps: bool = False

    @property
    def model_levels(self) -> int:
        """Number of 2× downsamplings — what spatial strategies divide H by.

        unet: one pool per width entry. milesial: the first width is the
        stem (inc) — pools = len(widths) − 1."""
        if self.model_arch == "milesial":
            n = len(self.model_widths) if self.model_widths else 5
            return n - 1
        return len(self.model_widths) if self.model_widths else 4

    # -- artifacts (paths mirror the reference layout, §1 layer map) --------
    checkpoint_dir: str = "./checkpoints"
    log_dir: str = "./logs"
    loss_dir: str = "./loss"
    checkpoint_name: Optional[str] = None  # -c flag: load this checkpoint
    # Mid-run checkpointing (crash recovery the reference lacks, SURVEY.md
    # §5 'Failure detection'): save every N epochs; 0 = final save only.
    checkpoint_every_epochs: int = 1
    # Keep a separate <method>_best.ckpt at the highest val Dice seen.
    save_best: bool = False
    # Serialize + write checkpoints on a background thread (the device→host
    # snapshot still happens inline — donated buffers force that): epoch
    # saves stop stalling the step loop. The trainer drains pending writes
    # before train() returns, so a checkpoint is always durable by the time
    # anything could read it. False = fully synchronous saves.
    async_checkpoint: bool = True
    # Stop when val loss has not improved for N consecutive epochs
    # (0 = off). Deterministic across processes: every rank sees the same
    # val loss (sharded eval returns identical values everywhere), so all
    # ranks stop together.
    early_stop_patience: int = 0

    # -- resilience (utils/faults.py, docs/RELIABILITY.md) ------------------
    # Policy when a train-step loss reads back non-finite (detection
    # piggybacks the metrics readback — zero cost on healthy runs):
    #   "abort"    raise NonFiniteLossError (default: fail loudly; under
    #              fit_with_restarts / --max-restarts this already retries
    #              from the last epoch checkpoint);
    #   "rollback" reload the newest intact checkpoint in-place and redo
    #              from its epoch, up to `rollback_retries` times, then
    #              abort;
    #   "skip"     check each step's loss synchronously (one device sync
    #              per step — costs pipeline overlap; state donation is
    #              disabled) and discard the update of any non-finite
    #              step. Incompatible with fused dispatch / grad accum.
    nonfinite_policy: str = "abort"
    rollback_retries: int = 2
    # Bounded exponential-backoff retries for transient host failures in
    # the data decode path and the placement worker (OSError family):
    # attempt i sleeps retry_backoff_s * 2**i. 0 retries = fail fast.
    data_retries: int = 3
    retry_backoff_s: float = 0.05
    # Dispatch watchdog: a step-loop iteration exceeding this many seconds
    # dumps the step-timeline tracer's per-phase spans and requests a
    # checkpoint-and-stop via the collective stop agreement. 0 = off.
    # The FIRST executed epoch is untimed (it compiles every executable
    # shape — minutes over a tunneled runtime — which would false-fire
    # any steady-state-sized timeout); coverage starts at epoch 2.
    step_timeout_s: float = 0.0
    # Checkpoint retention: keep the newest N files per checkpoint path
    # (<tag>.ckpt, <tag>.ckpt.1, ...). Restore verifies each file's
    # content hash and falls back to the newest intact one, so N >= 2
    # makes a torn newest file recoverable. 1 = overwrite in place.
    keep_checkpoints: int = 2
    # Deterministic fault injection (tests / drills): "site[@rank]:
    # epoch:step[:count]" specs, sites in utils/faults.SITES ("@rank"
    # pins a fault to one process of a multi-process job). Empty = inert.
    inject_faults: Tuple[str, ...] = ()
    # Elastic runtime (dist/health.py, dist/elastic.py): when set, the
    # trainer writes a per-rank beat file (rank_R.beat) into this
    # directory from a daemon thread — the supervisor's failure
    # detector. The step loop only assigns attributes per iteration
    # (no host sync, no collective); the thread writes at
    # heartbeat_interval_s cadence. None = no heartbeat (non-elastic
    # runs are untouched). Normally armed by the supervisor, which
    # appends --heartbeat-dir to every worker it launches.
    heartbeat_dir: Optional[str] = None
    heartbeat_interval_s: float = 0.5

    # -- synthetic data (tests / benches without the Carvana download) ------
    synthetic_samples: int = 0  # >0: use an in-memory procedural dataset

    # -- memory -------------------------------------------------------------
    # Rematerialize the forward during backward (jax.checkpoint): ~half the
    # activation HBM for ~1/3 more FLOPs. Off by default (HBM is ample at
    # the reference config); turn on for big batches / high resolutions.
    remat: bool = False

    # -- kernels (ops/kernels.py, docs/PERFORMANCE.md "Kernels") ------------
    # The Pallas kernel-engagement policy, --kernels:
    #   "xla"     no Pallas fast paths — every output bit-identical to
    #             the historical paths (the correctness reference);
    #   "pallas"  the full kernel tier: fused training-loss stats
    #             (ops/fused_loss.py), one-pass eval stats
    #             (ops/pallas_kernels.py), the fused DoubleConv
    #             BN+ReLU epilogue (milesial), and the serve tier's
    #             sigmoid/threshold mask kernel — each individually
    #             revoked by a per-chip Mosaic probe priors file
    #             (kernel_priors / DPT_KERNEL_PRIORS) that marks it
    #             rejected, falling back bit-identically to XLA.
    kernels: str = "xla"
    # Per-chip Mosaic probe priors file (tools/probe_kernels.py →
    # ops/kernels.load_priors): kernels the chip's compiler rejected
    # disengage loudly. None = also honors $DPT_KERNEL_PRIORS.
    kernel_priors: Optional[str] = None
    # LEGACY alias (pre-policy flag, kept like compute_dtype → --dtype):
    # True resolves to its historical engagement set — the fused
    # training loss + eval stats kernels only — with a loud log. An
    # explicit kernels="pallas" supersedes it. Prefer --kernels.
    use_pallas: bool = False

    # -- dispatch amortization ----------------------------------------------
    # K optimizer steps per XLA dispatch (lax.scan over K stacked batches).
    # Semantically identical to K single steps on the same data; amortizes
    # per-dispatch runtime latency, which dominates step time on remote /
    # tunneled TPU runtimes. 1 = one dispatch per step (reference-shaped).
    steps_per_dispatch: int = 1

    # -- gradient accumulation ----------------------------------------------
    # ONE optimizer step per K loader batches (effective batch K·b) with
    # one batch's activation memory — EXACT for the non-additive log-dice
    # loss via the two-pass stats/cotangent scheme (train/steps.py
    # make_accum_train_step). Stateless models only; mutually exclusive
    # with steps_per_dispatch > 1. An epoch's trailing batches that don't
    # fill K train as ordinary single steps.
    grad_accum: int = 1

    # -- observability (distributedpytorch_tpu/obs, docs/OBSERVABILITY.md) --
    metric_every_steps: int = 10  # reference records every 10 (train_utils.py:75)
    profile_dir: Optional[str] = None  # jax.profiler trace capture when set
    # Step-timeline tracer (utils/trace.py): per-phase host spans
    # (decode/stack/h2d/dispatch/readback) appended to this JSONL path;
    # summarized by bench.py, exported to Perfetto by obs/trace_hub.py.
    # Multi-process runs: rank 0 writes the path, rank R appends .rankR.
    # None = JSONL off (spans still feed the flight recorder's ring).
    timeline_path: Optional[str] = None
    # Serve GET /metrics (Prometheus text exposition of the process-wide
    # registry) + /healthz on this port for the run's lifetime. Rank R of
    # a multi-process job binds port+R (one scrape target per rank).
    # 0 = ephemeral (tests read trainer.metrics_server.port); None = off.
    metrics_port: Optional[int] = None
    # On-demand device profile over a step range: capture a
    # jax.profiler trace from global step N until M (inclusive:exclusive)
    # into profile_dir (default <log_dir>/profile). None = off.
    profile_steps: Optional[Tuple[int, int]] = None

    @property
    def precision(self):
        """Convenience accessor for the resolved
        :class:`~distributedpytorch_tpu.ops.precision.PrecisionPolicy`.
        The resolver is ``ops.precision.get_policy(config)`` (honoring
        the legacy ``compute_dtype`` override) — layers call it directly
        because it also accepts duck-typed configs; this property wraps
        the same call for TrainConfig holders, so there is exactly one
        resolution path."""
        from distributedpytorch_tpu.ops.precision import get_policy

        return get_policy(self)

    @property
    def kernel_policy(self):
        """Convenience accessor for the resolved
        :class:`~distributedpytorch_tpu.ops.kernels.KernelPolicy` — the
        resolver is ``ops.kernels.get_kernel_policy(config)`` (honoring
        the legacy ``use_pallas`` alias and the Mosaic probe priors);
        this property wraps the same call, so there is exactly one
        resolution path (the precision property's pattern)."""
        from distributedpytorch_tpu.ops.kernels import get_kernel_policy

        return get_kernel_policy(self)

    @property
    def val_fraction(self) -> float:
        return self.val_percent / 100.0

    @property
    def method_tag(self) -> str:
        """Artifact directory tag, e.g. ./loss/<tag>/ and ./logs/<tag>.log."""
        return self.train_method


@dataclasses.dataclass
class ServeConfig:
    """The serving tier's knobs (serve/, docs/SERVING.md) — what
    ``python -m distributedpytorch_tpu serve`` parses into and what
    ``tools/bench_serve.py`` sweeps over.

    Model-identity fields (arch/widths/geometry/s2d) must match the
    trained checkpoint, exactly like predict.py's flags — both surfaces
    resolve them through the same ``serve/infer.load_inference_bundle``.
    """

    # -- model / checkpoint (must match training) ---------------------------
    checkpoint: str = ""
    checkpoint_dir: str = "./checkpoints"
    image_size: Tuple[int, int] = (960, 640)  # (W, H), CLI flag order
    model_arch: str = "unet"
    model_widths: Optional[Tuple[int, ...]] = None
    s2d_levels: int = -1
    threshold: float = 0.5
    # Weights-only quantization for the serving path (--quantize):
    #   None   — serve the checkpoint's own float weights;
    #   "int8" — per-output-channel symmetric int8 weights resident on
    #            device (param bytes quartered vs f32), dequantized
    #            inside the AOT-compiled forward. Accepts either a
    #            regular checkpoint (quantized on load) or a file
    #            written by tools/quantize.py (which also records the
    #            source hash in its manifest). Dice parity vs the float
    #            checkpoint is pinned by tests/test_quantize.py.
    quantize: Optional[str] = None
    # Kernel-engagement policy for the serving path (--kernels,
    # ops/kernels.py): "pallas" traces the fused sigmoid/threshold mask
    # kernel INSIDE every AOT bucket executable — the executable returns
    # the {0,255} uint8 mask itself (1 byte/pixel D2H instead of 4 f32,
    # no host threshold pass), bit-identical to the "xla" path's
    # postprocess at the operating threshold. Honors the Mosaic probe
    # priors exactly like training.
    kernels: str = "xla"
    kernel_priors: Optional[str] = None
    # Content-addressed AOT executable store (--aot-cache,
    # utils/aotstore.py, docs/PERFORMANCE.md "AOT executable store"):
    # startup LOADS each bucket executable from this directory instead
    # of compiling on hit, compiles-and-persists on miss; corrupt or
    # version-skewed entries are refused loudly and recompiled. None =
    # resolve from $DPT_AOT_CACHE (unset = off); "" = force off.
    aot_cache: Optional[str] = None

    # -- batching -----------------------------------------------------------
    # The padded bucket ladder: every dispatch rides one of exactly these
    # batch shapes, each AOT-compiled per replica at startup (first
    # request pays zero compiler time). More buckets = less padding but
    # more startup compiles.
    bucket_sizes: Tuple[int, ...] = (1, 2, 4, 8)
    # Latency SLO for the batching wait: a request is flushed (in the
    # smallest covering bucket) at most this long after admission even
    # if its bucket never fills.
    slo_ms: float = 50.0
    # Work-conserving dispatch: with an idle replica, flush immediately
    # instead of waiting for the SLO — batches form exactly when
    # capacity (not the clock) is the bottleneck. False = pure SLO
    # batching (throughput-biased; useful for bench A/Bs).
    eager_when_idle: bool = True
    # Pending-image admission cap (None = 4x the largest bucket): beyond
    # it submits are rejected ("overloaded"), so queue depth — and with
    # it queueing latency — is bounded by construction under overload.
    queue_cap_images: Optional[int] = None

    # -- execution ----------------------------------------------------------
    # Data-parallel replica groups over the local devices (clamped to
    # the devices present). Serving is collective-free: N replicas serve
    # N concurrent buckets independently.
    replicas: int = 1
    # Buckets stacked + H2D-placed ahead of dispatch on the placement
    # worker (utils/prefetch.pipelined_placement); 0 = synchronous.
    placement_depth: int = 2
    # Dispatched-but-undrained buckets allowed per replica: the device
    # queue keeps one bucket behind the executing one (H2D overlaps
    # compute) but can never absorb unbounded backlog — in-flight slots
    # return at COMPLETION, so total work-in-system stays bounded and
    # overload surfaces as rejections instead of silent latency growth.
    inflight_per_replica: int = 2
    # None = one drain thread per in-flight slot (the drain pool must
    # never be the throughput ceiling).
    completion_workers: Optional[int] = None
    # SampleCache budget (MiB) for path-keyed request decode; 0 = off.
    host_cache_mb: int = 256
    # Clipper-style prediction cache (serve/cache.py): exact-match
    # masks keyed on the decoded-input hash + weights version, bounded
    # LRU over this byte budget. 0 = off.
    predict_cache_mb: int = 0

    # -- self-healing (serve/server.py, docs/SERVING.md "Fleet") ------------
    # In-process dispatch-core relaunch budget: a dead dispatch loop
    # rebuilds (fresh queue + thread against the same AOT engine) up to
    # this many times with exponential backoff; exhausted = the server
    # goes terminal so a process supervisor (elastic --workload serve)
    # relaunches the whole worker.
    restart_limit: int = 3
    restart_backoff_s: float = 0.25
    # Elastic supervision (dist/elastic.py --workload serve): when set,
    # the serve worker writes per-rank beat files — the dispatch loop
    # ticks progress every turn, so a wedged pipeline stops the ticks
    # and the supervisor's progress timeout catches it. Normally armed
    # by the supervisor itself.
    heartbeat_dir: Optional[str] = None
    heartbeat_interval_s: float = 0.5
    # Deterministic chaos (utils/faults.py serve sites:
    # serve_dispatch_death / serve_replica_wedge / serve_decode /
    # swap_crash) — drills the relaunch and rollback paths.
    inject_faults: Tuple[str, ...] = ()

    # -- weight rollout (serve/rollout.py) ----------------------------------
    # Replica groups the candidate canaries on before promotion.
    canary_replicas: int = 1
    # Health-watch window: the canary serves real traffic this long
    # before the gauges + Dice probe judge it.
    rollout_window_s: float = 5.0
    # Pinned-sample probe images (paths, decoded through the engine);
    # empty = gauge-only gating. The canary's masks must score within
    # rollout_dice_margin of the old weights' masks on these samples.
    rollout_probe: Tuple[str, ...] = ()
    rollout_dice_margin: float = 0.02
    # Poll this checkpoint path and roll out (canaried) whenever the
    # file is replaced; None = off. The serve CLI's --watch-checkpoint
    # defaults it to the serving checkpoint's own path.
    watch_checkpoint: Optional[str] = None
    watch_poll_s: float = 2.0

    # -- autoscale (serve/autoscale.py hint + serve/scaler.py actuator) -----
    # Cadence of the replica-count recommendation (gauge + log line)
    # from queue-depth/shed hysteresis. 0 = off.
    autoscale_interval_s: float = 30.0
    # ACT on the hint: grow/shrink the live replica group through
    # Server.resize_replicas (AOT-store-backed, no restart). Requires
    # the hint (autoscale_interval_s > 0); off by default — actuation
    # is opt-in, the hint alone is free.
    autoscale_act: bool = False
    # dpt_serve_plan artifact (analysis/serve_planner.py plan-serve):
    # every scale decision cites the grid point it executes. None =
    # decisions still happen, cited as plan_point=None.
    serve_plan: Optional[str] = None
    # Actuation bounds + anti-flap cooldown (None = the hint's own
    # hysteresis window count).
    min_replicas: int = 1
    max_replicas: Optional[int] = None
    scale_cooldown_windows: Optional[int] = None

    # -- sustained A/B (serve/rollout.py ABTest; POST /admin/ab) ------------
    # Arm "b" traffic fraction when an A/B starts without an explicit
    # split in the request body.
    ab_split: float = 0.5

    # -- request tracing (obs/reqtrace.py, docs/OBSERVABILITY.md) -----------
    # End-to-end "good request" latency bound the SLO burn-rate windows
    # judge against. None = 2x slo_ms (the batching wait plus a
    # comparable service allowance).
    latency_slo_ms: Optional[float] = None
    # Structured-log threshold: any served request slower than this logs
    # ONE JSON line with its id + full span ledger (and lands in the
    # flight ring). <= 0 = 2x the latency SLO.
    slow_request_ms: float = 0.0
    # Per-request span JSONL (the serve analogue of --trace-timeline on
    # training runs): rank 0 writes the path, rank R appends .rankR; the
    # elastic supervisor arms it per attempt and merges the workers into
    # one fleet Perfetto timeline. None = no span export (the ledger
    # ring, /stats attribution, slow-request log, and flight-ring
    # reject/slow events all stay on regardless).
    trace_timeline: Optional[str] = None
    # Arrival-trace recording (serve/sim.py ArrivalRecorder): one
    # bounded JSONL line per ingress (wall-time, decoded rows/shape,
    # covering bucket) — the recorded-trace input `plan-serve` replays
    # against a profiled service-time model. None = off; the line cap
    # bounds the file for long-running servers.
    record_arrivals: Optional[str] = None
    record_arrivals_limit: int = 200_000

    # -- transport ----------------------------------------------------------
    host: str = "127.0.0.1"
    port: int = 8008
