"""Loss/throughput records with reference artifact parity.

The reference's only observability is (a) a message-only logfile and (b)
pandas DataFrames pickled to ``./loss/{method}/{train,val}_loss.pkl`` with
columns ``['Step', 'Time', 'Loss']`` — a train row every 10 steps holding the
mean of the last ≤10 losses, and a val row per epoch (reference
utils/train_utils.py:75-79, 82-84, 89-92). `LossRecords` reproduces that
format exactly (it is the imgs/sec comparison source, SURVEY.md §6) and adds
what the reference lacks: imgs/sec accounting and a val-Dice column written
to a separate file so the pickle schema stays reference-compatible.

Unlike the reference, the output directory is created on demand — the
reference crashes at save time because ``./loss/{method}/`` never exists
(SURVEY.md §2 component 13).

Non-blocking by design (the async step pipeline's readback leg): a
metrics row falling due no longer forces the device→host pull on the
spot. The row's window of device scalars is parked as *pending* — with a
best-effort ``copy_to_host_async`` started immediately, so the bytes
stream back under later dispatches — and materialized at the NEXT row
boundary (by which time its steps are a full window old and the copies
have landed: no stall) or at any flush point (epoch validation,
checkpoint ``state_dict``, ``save``). Values are bit-identical to the
blocking scheme; only when the host blocks changes.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

from distributedpytorch_tpu.obs import defs as obsm
from distributedpytorch_tpu.utils.trace import NULL_TIMELINE


def _start_async_copy(x) -> None:
    """Kick off a non-blocking device→host copy where the array supports
    it (jax.Array does; plain floats and lazy callables don't need it)."""
    try:
        x.copy_to_host_async()
    except AttributeError:
        pass


class LossRecords:
    """Accumulates train/val loss rows and writes reference-format pickles."""

    def __init__(
        self,
        method_tag: str,
        loss_dir: str = "./loss",
        every: int = 10,
        tracer=None,
        nonfinite_hook=None,
    ):
        self.method_tag = method_tag
        self.loss_dir = loss_dir
        self.every = every
        self.tracer = tracer or NULL_TIMELINE
        # non-finite loss detection piggybacked on the readback (the drain
        # already materializes every loss to a host float — checking it is
        # free): called as hook(step, value) on the first non-finite value
        # of each drained window. The trainer's failure policies hang off
        # this (train/loop.py); None = no detection (standalone users).
        self.nonfinite_hook = nonfinite_hook
        self.start_time = time.time()
        self.losses: List[float] = []
        self.train_rows: List[list] = []  # [step, time_s, mean-of-last-10 loss]
        # rows due but not yet drained to host: [step, time_s, lo, hi] with
        # (lo, hi) the window's index range in self.losses
        self._pending_rows: List[list] = []
        self.val_rows: List[list] = []  # [step, time_s, val loss]
        self.dice_rows: List[list] = []  # [step, time_s, val dice] (new)
        self.images_seen = 0
        # Steady-state throughput reference point: set when the FIRST train
        # step has been recorded, so XLA compile + warmup of step 1 are
        # excluded from images_per_second (VERDICT.md round 2 item 10).
        self._steady_t0: Optional[float] = None
        self._steady_images0 = 0

    def record_train(self, step: int, loss, batch_images: int = 0) -> None:
        """Call once per optimizer step with the UNSCALED loss
        (reference train_utils.py:67, 75-79).

        `loss` may be a device scalar OR a zero-arg callable returning one
        (the multi-step path defers slicing its (K,) loss array until its
        row drains — slicing eagerly would issue K extra device dispatches
        and undo the dispatch amortization). Nothing blocks here: a due
        row drains the PREVIOUS pending row (its async copies are a full
        window old) and parks its own window for the next boundary."""
        self.losses.append(loss)
        self.images_seen += batch_images
        obsm.TRAIN_STEPS.inc()
        if batch_images:
            obsm.TRAIN_IMAGES.inc(batch_images)
        if self._steady_t0 is None:
            # step 1 just ran (its dispatch included the jit trace+compile):
            # start the steady-state clock here and don't count its images
            self._steady_t0 = time.time()
            self._steady_images0 = self.images_seen
        if step % self.every == 0:
            self.drain()
            lo = max(0, len(self.losses) - self.every)
            hi = len(self.losses)
            for x in self.losses[lo:hi]:
                _start_async_copy(x)
            self._pending_rows.append(
                [step, time.time() - self.start_time, lo, hi]
            )

    def drain(self) -> None:
        """Materialize pending rows: force their loss windows to host (the
        pipeline's ``readback`` phase) and append the finished
        [step, time, mean] rows. The Time column keeps the timestamp of
        when the row fell DUE, not when it drained."""
        if not self._pending_rows:
            return
        pending, self._pending_rows = self._pending_rows, []
        with self.tracer.span("readback", rows=len(pending)):
            for step, ts, lo, hi in pending:
                window = [
                    float(x() if callable(x) else x) for x in self.losses[lo:hi]
                ]
                self.losses[lo:hi] = window
                self.train_rows.append([step, ts, float(np.mean(window))])
                # telemetry rides the drain the pipeline already does —
                # the one place a train-loss value is a host float for free
                obsm.TRAIN_LOSS.set(self.train_rows[-1][2])
                if self.nonfinite_hook is not None:
                    for v in window:
                        if not np.isfinite(v):
                            # the hook may raise (abort/rollback policies);
                            # this row is already appended, so the curve
                            # shows WHERE the run went non-finite
                            self.nonfinite_hook(step, v)
                            break

    def state_dict(self) -> dict:
        """Serializable metric history for checkpointing (msgpack-plain:
        nested lists and numbers only). Pending rows and lazy losses are
        forced — the checkpoint must not hold device references."""
        self.drain()
        window = [float(x() if callable(x) else x) for x in self.losses]
        self.losses[:] = window
        if self.nonfinite_hook is not None:
            # the sub-window since the last due row is only ever forced
            # HERE (drain checks whole rows): without this, a NaN landing
            # between row boundaries would be checkpointed as healthy
            # state and detection would miss it entirely
            for v in window[-self.every:]:
                if not np.isfinite(v):
                    self.nonfinite_hook(len(window), v)
                    break
        return {
            "train_rows": [list(map(float, r)) for r in self.train_rows],
            "val_rows": [list(map(float, r)) for r in self.val_rows],
            "dice_rows": [list(map(float, r)) for r in self.dice_rows],
            # sub-window losses recorded since the last row: without them a
            # resume would under-fill the next mean-of-last-N row and drop
            # those steps from the curve entirely
            "window": window[-self.every :],
            "images_seen": int(self.images_seen),
            "elapsed": float(self.elapsed),
        }

    def load_state_dict(self, state: dict) -> None:
        """Resume metric history: rows append after the restored ones and
        the Time column stays monotonic (start_time is shifted so restored
        elapsed time is accounted for)."""
        self.train_rows = [[int(r[0]), float(r[1]), float(r[2])] for r in state["train_rows"]]
        self.val_rows = [[int(r[0]), float(r[1]), float(r[2])] for r in state["val_rows"]]
        self.dice_rows = [[int(r[0]), float(r[1]), float(r[2])] for r in state["dice_rows"]]
        self.images_seen = int(state["images_seen"])
        self.start_time = time.time() - float(state["elapsed"])
        self.losses = [float(x) for x in state.get("window") or []]
        self._pending_rows = []
        # throughput clock restarts at the resumed run's first step (its
        # compile is excluded just like a fresh run's)
        self._steady_t0 = None
        self._steady_images0 = 0

    def record_val(self, step: int, val_loss: float, val_dice: Optional[float] = None) -> None:
        self.drain()  # epoch boundary: the epoch's train rows land first
        now = time.time() - self.start_time
        self.val_rows.append([step, now, float(val_loss)])
        if val_dice is not None:
            self.dice_rows.append([step, now, float(val_dice)])
        obsm.TRAIN_VAL_LOSS.set(float(val_loss))
        if val_dice is not None:
            obsm.TRAIN_VAL_DICE.set(float(val_dice))
        obsm.TRAIN_IMGS_PER_S.set(self.images_per_second())

    @property
    def elapsed(self) -> float:
        return time.time() - self.start_time

    def images_per_second(self) -> float:
        """Steady-state throughput: images per wall-second measured from the
        end of the first recorded step, so the first step's compile time is
        not in the denominator. 0.0 until two steps have been recorded."""
        if self._steady_t0 is None:
            return 0.0
        dt = time.time() - self._steady_t0
        images = self.images_seen - self._steady_images0
        return images / dt if dt > 0 and images > 0 else 0.0

    def save(self) -> None:
        """Write ``{train,val}_loss.pkl`` (reference schema) + ``val_dice.pkl``."""
        import pandas as pd

        self.drain()

        out = os.path.join(self.loss_dir, self.method_tag)
        os.makedirs(out, exist_ok=True)
        pd.DataFrame(self.train_rows, columns=["Step", "Time", "Loss"]).to_pickle(
            os.path.join(out, "train_loss.pkl")
        )
        pd.DataFrame(self.val_rows, columns=["Step", "Time", "Loss"]).to_pickle(
            os.path.join(out, "val_loss.pkl")
        )
        pd.DataFrame(self.dice_rows, columns=["Step", "Time", "Dice"]).to_pickle(
            os.path.join(out, "val_dice.pkl")
        )
