"""Determinism helpers.

Parity with the reference `set_seed` (reference utils/utils.py:28-35), which
seeds python/numpy/torch and sets PYTHONHASHSEED + cuDNN toggles. On TPU, XLA
compilation is deterministic by default, and JAX randomness is explicit
(`jax.random.key`), so this shrinks to seeding the host-side RNGs (data
shuffling, splits) and exporting PYTHONHASHSEED.
"""

from __future__ import annotations

import os
import random

import jax
import numpy as np


def set_seed(seed: int) -> jax.Array:
    """Seed host RNGs; returns the root `jax.random` key for device RNG."""
    random.seed(seed)
    np.random.seed(seed)
    os.environ["PYTHONHASHSEED"] = str(seed)
    return jax.random.key(seed)
