"""Version-tolerant imports for JAX APIs that moved between releases.

`shard_map` has lived in three places across the jax versions this repo
must run under: ``jax.experimental.shard_map.shard_map`` (≤0.4.x, keyword
``check_rep``), ``jax.shard_map`` (≥0.5, keyword ``check_vma``), and a
transitional window exporting both. The schedule code (parallel/pipeline.py,
ops/fused_loss.py) always calls the modern surface —
``shard_map(f, mesh=..., in_specs=..., out_specs=..., check_vma=...)`` —
and this shim maps the replication-check keyword onto whatever the
installed jax actually accepts. The seed's bare ``from jax import
shard_map`` was the single root cause of the 23-failure/5-error tier-1
run on jax 0.4.37.
"""

from __future__ import annotations

import inspect


def _resolve():
    try:
        from jax import shard_map as sm  # jax >= 0.5
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
    if not callable(sm):  # a transitional jax exported the MODULE jax.shard_map
        sm = sm.shard_map
    params = inspect.signature(sm).parameters
    if "check_vma" in params:
        rep_kw = "check_vma"
    elif "check_rep" in params:
        rep_kw = "check_rep"
    else:
        rep_kw = None  # keyword dropped entirely: checking is not optional
    return sm, rep_kw


_SHARD_MAP, _REP_KW = _resolve()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` under every supported jax: ``check_vma`` is passed
    through as ``check_rep`` on versions predating the rename (identical
    role: disable the replication/varying-axes output check), and dropped
    where no such keyword exists."""
    kwargs = {}
    if _REP_KW is not None:
        kwargs[_REP_KW] = check_vma
    return _SHARD_MAP(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
