"""Step-timeline tracer: per-phase host timestamps for the async pipeline.

The fully-overlapped step loop (train/loop.py + utils/prefetch.py) runs
five host-observable phases per batch —

    decode    host-side sample decode / batch assembly (data/loader.py)
    stack     np.stack of K per-step batches into one dispatch payload
    h2d       host→device placement (strategy.place_work on the worker)
    dispatch  the host-side step call (async: enqueue, not execution)
    readback  device→host drain of loss scalars (utils/metrics.py)

— and whether they actually overlap is invisible in aggregate throughput
numbers. This tracer records ``(phase, t0, t1)`` wall spans (a shared
``time.perf_counter`` clock across every thread: loader pool, placement
worker, main loop), appends them as JSONL, and summarizes per-phase
totals so a throughput regression is attributable to the phase that
grew. `bench.py` emits the summary alongside imgs/sec; the overlap test
(tests/test_async_pipeline.py) asserts on the raw spans.

Disabled (the default: no path) it is a no-op cheap enough to leave the
call sites unconditional.

The telemetry layer (distributedpytorch_tpu/obs) rides these call
sites: every completed span ALSO lands in the flight recorder's bounded
ring (obs/flight.py) whether JSONL tracing is on or not — that is what
makes a crash dump's tail identify the phase a dead run was in — and
events carry a ``rank`` tag plus a wall-clock anchor so the trace hub
(obs/trace_hub.py) can merge per-rank JSONL files into one Perfetto
timeline with cross-rank-comparable timestamps (``t0``/``t1`` stay
``perf_counter`` values, whose origin is per-process).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Dict, Iterable, List, Optional

from distributedpytorch_tpu.obs import flight

PHASES = ("decode", "stack", "h2d", "dispatch", "readback")


class StepTimeline:
    """Collects per-phase spans; thread-safe; JSONL-append on flush().

    ``path=None`` disables collection entirely unless ``enabled=True`` is
    forced (in-memory mode — what bench.py uses for its inline summary).
    Even disabled, completed spans feed the flight recorder's ring
    (bounded, allocation = the ring slot) unless ``DPT_OBS=0``.
    """

    def __init__(self, path: Optional[str] = None, *,
                 enabled: Optional[bool] = None, rank: int = 0):
        self.path = path
        self.enabled = (path is not None) if enabled is None else enabled
        self.rank = int(rank)
        self._events: List[dict] = []
        self._lock = threading.Lock()
        # per-phase running totals survive flush(): the summary covers the
        # whole run even though events are dumped incrementally
        self._totals: Dict[str, List[float]] = {}  # phase -> [count, total_s]

    def record(self, phase: str, t0: float, t1: float,
               wall: Optional[float] = None, **tags) -> None:
        """``wall`` defaults to now — right for spans recorded at their
        own end (the ``span()`` context manager). Callers that record a
        request's WHOLE ledger at completion (obs/reqtrace.py) pass each
        phase's true end-of-phase wall time instead, so the trace hub's
        ``wall − (t1 − t0)`` anchor lands every phase at its real start
        rather than collapsing them all onto the completion instant."""
        flight.record_span(phase, t0, t1, rank=self.rank, **tags)
        if not self.enabled:
            return
        event = {"phase": phase, "t0": round(t0, 6), "t1": round(t1, 6),
                 "wall": round(wall if wall is not None else time.time(), 6),
                 "rank": self.rank, **tags}
        with self._lock:
            self._events.append(event)
            acc = self._totals.setdefault(phase, [0, 0.0])
            acc[0] += 1
            acc[1] += t1 - t0

    @contextlib.contextmanager
    def span(self, phase: str, **tags):
        if not self.enabled and not flight.get().enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(phase, t0, time.perf_counter(), **tags)

    def events(self, phase: Optional[str] = None) -> List[dict]:
        """Unflushed events (optionally one phase), in record order."""
        with self._lock:
            evs = list(self._events)
        return [e for e in evs if phase is None or e["phase"] == phase]

    def flush(self) -> None:
        """Append collected events to ``path`` as JSONL and clear them
        (totals persist). In-memory mode just clears."""
        with self._lock:
            evs, self._events = self._events, []
        if not evs or self.path is None:
            return
        with open(self.path, "a") as f:
            for e in evs:
                f.write(json.dumps(e) + "\n")

    def summary(self) -> Dict[str, Optional[dict]]:
        """Per-phase ``{count, total_ms, mean_ms}`` over the whole run;
        phases never observed report None (distinguishable from 0 ms)."""
        with self._lock:
            totals = {k: list(v) for k, v in self._totals.items()}
        return _format_totals(totals)


def _format_totals(totals: Dict[str, List[float]]) -> Dict[str, Optional[dict]]:
    """phase → [count, total_s] accumulators → the summary shape shared by
    StepTimeline.summary and summarize_events (one formatter: bench.py
    emits both side by side, and they must never drift apart)."""
    out: Dict[str, Optional[dict]] = {}
    for phase in PHASES:
        if phase not in totals:
            out[phase] = None
            continue
        count, total = totals[phase]
        out[phase] = {
            "count": int(count),
            "total_ms": round(1e3 * total, 3),
            "mean_ms": round(1e3 * total / count, 3) if count else 0.0,
        }
    return out


#: Shared disabled instance for call sites whose owner passed no tracer.
NULL_TIMELINE = StepTimeline(None)


def load_events(path: str) -> List[dict]:
    """Parse a timeline JSONL file, skipping torn/blank lines (the file is
    appended mid-run; a concurrent reader can catch a partial line)."""
    events = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if isinstance(d, dict) and "phase" in d:
                events.append(d)
    return events


def summarize_events(events: Iterable[dict]) -> Dict[str, Optional[dict]]:
    """Same per-phase shape as :meth:`StepTimeline.summary`, from raw
    events (e.g. a trainer-written JSONL read back by bench.py)."""
    totals: Dict[str, List[float]] = {}
    for e in events:
        try:
            dt = float(e["t1"]) - float(e["t0"])
        except (KeyError, TypeError, ValueError):
            continue
        acc = totals.setdefault(e["phase"], [0, 0.0])
        acc[0] += 1
        acc[1] += dt
    return _format_totals(totals)


def summarize_timeline(path: str) -> Dict[str, Optional[dict]]:
    return summarize_events(load_events(path))
