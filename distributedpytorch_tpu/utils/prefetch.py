"""Bounded prefetch helpers shared by the input pipeline.

Two variants of the same submit-ahead/pop/yield shape, differing in who
runs the work and what happens when the consumer walks away:

* :func:`bounded_prefetch` — a single daemon worker thread. For work that
  may block indefinitely on an external runtime (host→device placement on
  a remote/tunneled TPU): a daemon thread can never block interpreter
  exit, and closing the generator (or breaking out of a ``for``) stops the
  worker within its put-poll interval instead of leaving it wedged on a
  full queue pinning device buffers.
* :func:`bounded_submit` — futures on a caller-owned executor. For
  parallel host-side work (image decode across a pool); abandoning the
  generator cancels everything still queued.

Both yield in submission order and re-raise worker exceptions at the
consumption point.
"""

from __future__ import annotations

import collections
import queue as queue_mod
import threading
from typing import Callable, Iterable, Iterator, Tuple, TypeVar

T = TypeVar("T")
R = TypeVar("R")

_DONE = object()


def bounded_prefetch(
    items: Iterable[T], fn: Callable[[T], R], depth: int = 2
) -> Iterator[Tuple[T, R]]:
    """Yield ``(item, fn(item))`` with ``fn`` running up to ``depth`` items
    ahead on a daemon thread.

    The bound counts results the worker holds: a semaphore permit is taken
    BEFORE ``fn`` runs and returned when the consumer pops the result, so
    at most ``depth`` worker-held results (+ the one the consumer is using)
    are alive at once — for device placement, that many batches of device
    memory, including at ``depth=1`` (the round-3 queue-based bound kept
    one extra: a blocked put held a result the accounting missed,
    ADVICE r03)."""
    in_flight = threading.Semaphore(max(1, depth))
    q: queue_mod.Queue = queue_mod.Queue()  # unbounded; the semaphore bounds
    stop = threading.Event()

    def worker():
        try:
            for item in items:
                # poll-acquire so a walked-away consumer (stop set) never
                # leaves the worker blocked forever on a permit
                while not in_flight.acquire(timeout=0.1):
                    if stop.is_set():
                        return
                if stop.is_set():
                    return
                q.put((item, fn(item)))
        except BaseException as exc:  # re-raised at the consumption point
            q.put(exc)
            return
        q.put(_DONE)

    threading.Thread(target=worker, daemon=True, name="dpt-prefetch").start()
    try:
        while True:
            payload = q.get()
            if payload is _DONE:
                return
            if isinstance(payload, BaseException):
                raise payload
            in_flight.release()  # the consumer owns this result now
            yield payload
    finally:
        stop.set()


def bounded_submit(
    pool, fn: Callable[[T], R], items: Iterable[T], depth: int = 2
) -> Iterator[R]:
    """Yield ``fn(item)`` results in order, keeping up to ``depth`` futures
    in flight on ``pool``; abandoning the generator cancels queued work."""
    pending: collections.deque = collections.deque()
    it = iter(items)

    def submit_next() -> bool:
        try:
            item = next(it)
        except StopIteration:
            return False
        pending.append(pool.submit(fn, item))
        return True

    try:
        for _ in range(max(1, depth)):
            if not submit_next():
                break
        while pending:
            fut = pending.popleft()
            # refill BEFORE blocking on the result: the pool keeps `depth`
            # items genuinely in flight while the consumer waits
            submit_next()
            yield fut.result()
    finally:
        for fut in pending:
            fut.cancel()
