"""Bounded prefetch helpers shared by the input pipeline.

Two variants of the same submit-ahead/pop/yield shape, differing in who
runs the work and what happens when the consumer walks away:

* :func:`bounded_prefetch` — a single daemon worker thread. For work that
  may block indefinitely on an external runtime (host→device placement on
  a remote/tunneled TPU): a daemon thread can never block interpreter
  exit, and closing the generator (or breaking out of a ``for``) stops the
  worker within its put-poll interval instead of leaving it wedged on a
  full queue pinning device buffers.
* :func:`bounded_submit` — futures on a caller-owned executor. For
  parallel host-side work (image decode across a pool); abandoning the
  generator cancels everything still queued.

Both yield in submission order and re-raise worker exceptions at the
consumption point.

On top of them sits the step-pipeline placement scheduler
(:func:`stacked_work` + :func:`pipelined_placement`): the trainer's epoch
stream of host batches becomes a stream of *work items* — K-stacks for the
fused-dispatch paths, singles for everything else — whose np.stack and
host→device placement run on the prefetch worker, ``depth`` items ahead of
the consuming step loop. That is what keeps the device dispatch queue
non-empty: batch N+1's H2D transfer rides under batch N's executing scan
instead of serializing behind it.

The serving tier (serve/server.py) runs the SAME scheduler on its
request path: flushed request buckets are the work items, and
``place_fn`` stacks + pads + H2D-places each bucket onto its claimed
replica's device, ``depth`` buckets ahead of the dispatch loop.
"""

from __future__ import annotations

import collections
import logging
import queue as queue_mod
import threading
from typing import Callable, Iterable, Iterator, Optional, Tuple, TypeVar

import numpy as np

from distributedpytorch_tpu.utils import faults
from distributedpytorch_tpu.utils.trace import NULL_TIMELINE

logger = logging.getLogger(__name__)

T = TypeVar("T")
R = TypeVar("R")

_DONE = object()


def bounded_prefetch(
    items: Iterable[T], fn: Callable[[T], R], depth: int = 2,
    name: str = "dpt-prefetch",
) -> Iterator[Tuple[T, R]]:
    """Yield ``(item, fn(item))`` with ``fn`` running up to ``depth`` items
    ahead on a daemon thread.

    The bound counts results the worker holds: a semaphore permit is taken
    BEFORE ``fn`` runs and returned when the consumer pops the result, so
    at most ``depth`` worker-held results (+ the one the consumer is using)
    are alive at once — for device placement, that many batches of device
    memory, including at ``depth=1`` (the round-3 queue-based bound kept
    one extra: a blocked put held a result the accounting missed,
    ADVICE r03)."""
    in_flight = threading.Semaphore(max(1, depth))
    q: queue_mod.Queue = queue_mod.Queue()  # unbounded; the semaphore bounds
    stop = threading.Event()

    def worker():
        try:
            for item in items:
                # poll-acquire so a walked-away consumer (stop set) never
                # leaves the worker blocked forever on a permit
                while not in_flight.acquire(timeout=0.1):
                    if stop.is_set():
                        return
                if stop.is_set():
                    return
                q.put((item, fn(item)))
        except BaseException as exc:  # re-raised at the consumption point
            q.put(exc)
            return
        q.put(_DONE)

    threading.Thread(target=worker, daemon=True, name=name).start()
    try:
        while True:
            payload = q.get()
            if payload is _DONE:
                return
            if isinstance(payload, BaseException):
                raise payload
            in_flight.release()  # the consumer owns this result now
            yield payload
    finally:
        stop.set()


# ---------------------------------------------------------------------------
# Step-pipeline placement scheduler (train/loop.py's epoch source)
# ---------------------------------------------------------------------------

#: Work-item kinds flowing through the pipeline: a plain per-step batch, or
#: a list of K same-shape batches destined for one fused dispatch
#: (steps_per_dispatch / grad_accum).
SINGLE = "single"
STACK = "stack"


def stacked_work(
    batches: Iterable[dict], stack_size: int, batch_size: int
) -> Iterator[Tuple[str, object]]:
    """Group an epoch's batch stream into pipeline work items.

    Only full, uniformly-shaped batches can stack into the scanned
    executable (their shapes must all match the compiled (K, B, ...)
    payload); a ragged batch flushes the partial group — each buffered
    batch re-emitted as a single, THEN the ragged one — and the epoch's
    trailing partial group drains the same way. This reproduces the
    trainer's historical inline buffering exactly, so the (K>1) loss
    sequence is bit-identical to the old loop's.

    ``stack_size <= 1`` degenerates to all-singles.
    """
    if stack_size <= 1:
        for b in batches:
            yield (SINGLE, b)
        return
    buffer: list = []
    for b in batches:
        if b["image"].shape[0] == batch_size:
            buffer.append(b)
            if len(buffer) == stack_size:
                yield (STACK, buffer)
                buffer = []
        else:
            for q in buffer:
                yield (SINGLE, q)
            buffer = []
            yield (SINGLE, b)
    for q in buffer:
        yield (SINGLE, q)


def pipelined_placement(
    work: Iterable[Tuple[str, object]],
    place_fn: Callable[[str, object], object],
    depth: int = 2,
    tracer=None,
    epoch: Optional[int] = None,
    max_retries: int = 0,
    retry_backoff_s: float = 0.05,
    name: str = "dpt-prefetch",
) -> Iterator[Tuple[Tuple[str, object], object]]:
    """Yield ``(work_item, placed)`` with stacking + H2D placement running
    up to ``depth`` items ahead on the prefetch worker.

    ``place_fn(kind, payload)`` is the strategy's placement entry
    (Strategy.place_work): for a STACK item the K host batches are
    np.stack'ed here first — on the worker thread, off the step loop —
    then placed as one (K, B, ...) payload. ``depth <= 0`` places inline
    on the consumer thread (the synchronous baseline; still traced), as a
    generator so ``contextlib.closing`` works identically either way.

    Transient placement failures (OSError family — a flapping runtime
    channel — and the injected ``placement`` fault, coordinates
    ``(epoch, seq)``) retry with bounded exponential backoff before the
    worker surfaces them (utils/faults.py).

    The ``stack``/``h2d`` tracer spans recorded here are what make the
    overlap observable: their wall-clock windows interleave with the
    consumer's ``dispatch`` spans when the pipeline is actually ahead.
    """
    tracer = tracer or NULL_TIMELINE
    counter = {"n": 0}

    def place(item):
        kind, payload = item
        seq = counter["n"]
        counter["n"] += 1
        if kind == STACK:
            with tracer.span("stack", seq=seq):
                payload = {
                    key: np.stack([b[key] for b in payload])
                    for key in payload[0]
                }
        with tracer.span("h2d", seq=seq, kind=kind):
            return faults.call_with_retries(
                lambda: place_fn(kind, payload),
                site="placement",
                retries=max_retries,
                backoff_s=retry_backoff_s,
                epoch=epoch,
                step=seq,
                log=logger,
            )

    if depth <= 0:
        return ((item, place(item)) for item in work)
    return bounded_prefetch(work, place, depth=depth, name=name)


def bounded_submit(
    pool, fn: Callable[[T], R], items: Iterable[T], depth: int = 2
) -> Iterator[R]:
    """Yield ``fn(item)`` results in order, keeping up to ``depth`` futures
    in flight on ``pool``; abandoning the generator cancels queued work."""
    pending: collections.deque = collections.deque()
    it = iter(items)

    def submit_next() -> bool:
        try:
            item = next(it)
        except StopIteration:
            return False
        pending.append(pool.submit(fn, item))
        return True

    try:
        for _ in range(max(1, depth)):
            if not submit_next():
                break
        while pending:
            fut = pending.popleft()
            # refill BEFORE blocking on the result: the pool keeps `depth`
            # items genuinely in flight while the consumer waits
            submit_next()
            yield fut.result()
    finally:
        for fut in pending:
            fut.cancel()
