"""Virtual-device provisioning env for CPU-mesh subprocesses.

jax backends initialize once per process, and the remote-TPU PJRT plugin in
this image dials out from sitecustomize at interpreter start — so a
process that wants an n-device virtual CPU mesh must have the right env
BEFORE its interpreter starts. Every self-provisioning entry point
(`__graft_entry__.dryrun_multichip`, `tools/bench_pipeline.py`, the test
conftest) needs the same three moves: pin JAX_PLATFORMS=cpu, rewrite
--xla_force_host_platform_device_count in XLA_FLAGS, and blank the relay's
pool var so nothing dials the TPU. ONE definition here so a future
addition (say, a new env var that must be cleared) lands everywhere.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Mapping, MutableMapping, NoReturn, Optional, Sequence


def provisioned_env(
    n_devices: int, base: Mapping[str, str] | None = None
) -> MutableMapping[str, str]:
    """A copy of ``base`` (default ``os.environ``) prepared for a subprocess
    that must see ``n_devices`` virtual CPU devices and never touch the
    tunneled TPU runtime."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={int(n_devices)}"
    ).strip()
    env["PALLAS_AXON_POOL_IPS"] = ""  # never dial the TPU relay
    return env


def maybe_reexec_provisioned(
    n_devices: int,
    sentinel: str,
    extra_env: Optional[Mapping[str, str]] = None,
) -> Optional[int]:
    """The self-provisioning entry-point dance, in one place: if
    ``sentinel`` is already set this process IS the provisioned child —
    return None and let the caller proceed. Otherwise re-run
    ``sys.argv`` under ``provisioned_env(n_devices)`` (plus ``extra_env``
    as setdefaults) and return the child's exit code for the caller to
    propagate. Used by tools/bench_pipeline.py and
    tools/convergence_run.py; __graft_entry__ keeps its own variant (it
    re-execs a ``-c`` command, not a script file)."""
    if os.environ.get(sentinel) == "1":
        return None
    env = provisioned_env(n_devices)
    for key, value in (extra_env or {}).items():
        env.setdefault(key, value)
    env[sentinel] = "1"
    return subprocess.run(
        [sys.executable, "-u", os.path.abspath(sys.argv[0])] + sys.argv[1:],
        env=env,
    ).returncode


def reexec_provisioned_cmd(n_devices: int, sentinel: str,
                           cmd: Sequence[str]) -> NoReturn:
    """Replace THIS process with ``cmd`` under ``provisioned_env`` —
    ``os.execvpe``, not a child process. The caller's PID is preserved,
    so whatever supervises it (CI's ``timeout``, a shell) signals the
    provisioned interpreter directly: there is no intermediate parent
    whose death would orphan a still-running child. For entry points
    that re-run a command rather than ``sys.argv`` as a script (the
    ``analyze`` CLI re-runs ``-m distributedpytorch_tpu``)."""
    env = provisioned_env(n_devices)
    env[sentinel] = "1"
    os.execvpe(cmd[0], list(cmd), env)
