"""Content-addressed on-disk store of serialized AOT serve executables.

Every serve worker pays (buckets x replicas) XLA compiles at startup —
minutes of redundant work on TPU for programs that are byte-identical
across incarnations of the same engine (fleet cold start, elastic
relaunch, repeated bench legs). This store persists each compiled
bucket executable once (``jax.experimental.serialize_executable``) and
loads it on every later cold start, turning startup from compile-bound
into load-bound.

**Keying.** An entry's key is a hash of everything that changes the
compiled program: the PR-13 ``engine_fingerprint`` (model arch /
resolution / widths / s2d / quantization / kernels — obs/reqtrace.py),
the bucket's concrete input shape + dtype, the resolved kernel policy
and on-device mask threshold, and the device the executable is pinned
to (serve executables carry a ``SingleDeviceSharding``; deserializing
restores that device assignment, so replica N's entry is only correct
for device N).

**Skew and corruption.** The runtime that compiled an entry (jax /
jaxlib versions, backend platform) is recorded in the entry header and
cross-checked at load — NOT folded into the key — so a version bump
refuses the stale entry *loudly* (``result="skew"``, a logged note,
counter + flight-ring event) and falls back to compile-and-persist.
This is the same loud-refusal idiom as the profile/priors loaders
(obs/reqtrace.load_profile, ops/kernels.load_priors): a corrupt or
skewed entry is a miss-with-note, never a crash, never a silent
wrong-program load.

**Torn writes.** Entries are written with the checkpoint.py writer
idiom: unique tmp name, sha256 integrity footer, atomic
``os.replace`` — a worker SIGKILLed mid-persist leaves at most a stale
``*.tmp.*`` file, never a torn entry that poisons the next cold start.
Co-launched ranks racing the same key both rename complete
same-content files, so one shared store dir serves a whole fleet
(unlike the per-rank XLA compilation-cache split in dist/elastic.py).

CLI: ``python -m distributedpytorch_tpu aot {warm,ls,gc}`` — prewarm a
bucket ladder from a checkpoint, inspect entries, bound disk with LRU
eviction. Store dir resolution everywhere: explicit ``--aot-cache`` /
engine arg wins, else ``$DPT_AOT_CACHE``, else the store is off.
"""

from __future__ import annotations

import contextlib
import hashlib
import itertools
import json
import logging
import os
import pickle
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

ENV_VAR = "DPT_AOT_CACHE"
KEY_SCHEME_ENV = "DPT_AOT_KEY_SCHEME"
ENTRY_KIND = "dpt_aot_executable"
ENTRY_VERSION = 1
ENTRY_SUFFIX = ".aotx"

_HASH_MAGIC = b"#DPT_AOT_SHA256:"
_FOOTER_LEN = len(_HASH_MAGIC) + 32
# unique tmp names: two replicas of one engine persisting different
# buckets concurrently must not clobber each other's tmp files
_TMP_COUNTER = itertools.count()

#: Runtime fields recorded in every entry header and cross-checked at
#: load. Deliberately NOT part of the key: a jaxlib upgrade must read
#: as a loud "skew" refusal on the existing entries, not a silent
#: cache reset.
RUNTIME_FIELDS = ("jax", "jaxlib", "backend")


class AOTEntryError(Exception):
    """One unusable store entry (torn, corrupt, or schema-broken) —
    always caught inside :meth:`AOTStore.load` and converted to a
    counted ``skew`` refusal."""


@contextlib.contextmanager
def no_xla_compilation_cache():
    """A window in which jax's persistent compilation cache is REALLY
    off — for both reads and writes.

    The AOT store replaces exactly what the XLA cache would provide, and
    the two must never compose: an executable rehydrated from the XLA
    cache serializes WITHOUT its backend kernel symbols, so a store
    entry written from (or a load routed through) a cache hit dies on
    the next deserialize with "Symbols not found". Flipping
    ``jax_enable_compilation_cache`` alone is NOT enough: jax memoizes
    "is the cache used" process-wide at the first compile
    (``compilation_cache.is_cache_used``), after which per-call flag
    flips are ignored. So the window resets that memoized state on the
    way in (re-checked lazily against the now-disabled flag) and again
    on the way out (so later ordinary compiles re-enable the cache).
    Disk contents are untouched either way.
    """
    import jax

    try:
        from jax._src import compilation_cache as _cc
        reset = _cc.reset_cache
    except Exception:  # pragma: no cover — future-jax fallback
        reset = lambda: None  # noqa: E731
    prev = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    reset()
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)
        reset()


def runtime_versions() -> Dict[str, str]:
    """The compiling/loading runtime's identity — a seam (tests fake a
    jaxlib bump by monkeypatching this module attribute)."""
    import jax
    import jaxlib

    return {
        "jax": str(jax.__version__),
        "jaxlib": str(jaxlib.__version__),
        "backend": str(jax.default_backend()),
    }


def device_key(device) -> str:
    """The key's device component for one replica device.

    Default (``exact``) scheme pins ``str(device)`` — the platform's
    full decoration, e.g. ``TPU_0(process=0,(0,0,0,0))`` — which is
    always correct but means identical chips in different processes of
    a pod slice (different coords in the decoration) never share
    entries. ``DPT_AOT_KEY_SCHEME=kind`` relaxes the component to
    ``platform:device_kind:ordinal``: same-kind chips at the same local
    ordinal produce the SAME key across hosts/processes/incarnations,
    so a shared store dir serves a whole fleet and a scaled-up replica
    group re-loads the entries any sibling (or a previous incarnation,
    or ``aot warm``) already persisted.

    The local ordinal stays IN the key under both schemes: a
    deserialized executable is pinned to its compile-time device and
    refuses inputs placed anywhere else, so ordinal N's entry is only
    correct for ordinal N. Skew-refusal semantics are unchanged — the
    scheme string lands in ``meta["device"]``, is recorded in the entry
    header, and is re-verified at load like every other meta field."""
    scheme = (os.environ.get(KEY_SCHEME_ENV) or "exact").strip().lower()
    if scheme == "kind":
        platform = getattr(device, "platform", "") or ""
        kind = getattr(device, "device_kind", "") or platform
        ordinal = getattr(device, "id", 0)
        return f"{platform}:{kind}:{int(ordinal)}"
    if scheme not in ("", "exact"):
        logger.warning(
            "unknown $%s=%r — falling back to the exact device-string "
            "scheme", KEY_SCHEME_ENV, scheme,
        )
    return str(device)


def entry_key(
    fingerprint: str,
    bucket: int,
    input_shape,
    input_dtype: str,
    *,
    kernels: str = "xla",
    mask_threshold: Optional[float] = None,
    quantized: bool = False,
    stateful: bool = False,
    device: str = "",
) -> Tuple[str, dict]:
    """(key, meta) for one bucket executable. ``meta`` is the exact
    dict the key hashes — it is recorded in the entry header and
    re-verified at load, so a hash collision or a tampered file can
    never load as the wrong program. ``mask_threshold`` is key material
    because the serve-mask kernel bakes the threshold into the traced
    program (serve/engine.py)."""
    meta = {
        "engine_fingerprint": str(fingerprint),
        "bucket": int(bucket),
        "input_shape": [int(s) for s in input_shape],
        "input_dtype": str(input_dtype),
        "kernels": str(kernels),
        "mask_threshold": (
            None if mask_threshold is None else float(mask_threshold)
        ),
        "quantized": bool(quantized),
        "stateful": bool(stateful),
        "device": str(device),
    }
    blob = json.dumps(meta, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16], meta


def _note(result: str, key: str = "", detail: str = "") -> None:
    """One store event: the counter family + the flight ring (a
    skew-storm at relaunch must be diagnosable post-mortem)."""
    from distributedpytorch_tpu.obs import defs as obsm
    from distributedpytorch_tpu.obs import flight

    obsm.AOT_CACHE.labels(result=result).inc()
    fields = {"result": result, "key": key}
    if detail:
        fields["detail"] = detail[:200]
    flight.record("aot_cache", **fields)


#: Markers XLA stamps into an executable's text when any input buffer is
#: aliased to an output (the compiled form of ``jit(...,
#: donate_argnums=...)``). Shared with analysis/donation.py, which scans
#: the LOWERED (pre-compile) text for the same property statically.
DONATION_MARKERS = ("input_output_alias", "tf.aliasing_output")


def executable_donates(compiled) -> bool:
    """Does this compiled executable alias an input buffer into an
    output? Such an executable frees (or overwrites) an operand on
    every call — admitting one to the store hands every sibling
    process a use-after-free: serve replicas re-read their weights
    operand on each request, so the second request through a
    rehydrated donated executable reads poisoned memory (the
    CPU-backend SIGABRT class). Unreadable text counts as donating —
    the store must be able to PROVE cleanliness to admit."""
    try:
        text = compiled.as_text()
    except Exception:  # noqa: BLE001 — no proof, no admission
        return True
    return any(marker in text for marker in DONATION_MARKERS)


class AOTStore:
    """One store directory; flat ``<key>.aotx`` entries."""

    def __init__(self, root: str):
        self.root = os.path.abspath(str(root))
        # per-engine-build story (serve /stats); the process-wide view
        # is the dpt_aot_cache_total counter family
        self.stats = {"hit": 0, "miss": 0, "skew": 0}

    @classmethod
    def resolve(cls, aot_cache=None) -> Optional["AOTStore"]:
        """Explicit arg > ``$DPT_AOT_CACHE`` > disabled (None). An
        empty-string arg disables even with the env var set; an
        already-built store passes through."""
        if isinstance(aot_cache, cls):
            return aot_cache
        root = (
            aot_cache if aot_cache is not None else os.environ.get(ENV_VAR)
        )
        return cls(root) if root else None

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}{ENTRY_SUFFIX}")

    # -- persist -------------------------------------------------------------
    def save(self, key: str, meta: dict, compiled) -> Optional[str]:
        """Serialize ``compiled`` and atomically persist it under
        ``key``. Never raises outward: a store that cannot persist
        (disk full, unserializable executable) logs a note and the
        engine simply stays uncached."""
        if executable_donates(compiled):
            logger.warning(
                "aot store: refusing to admit %s — the executable "
                "aliases an input buffer to an output (donation); a "
                "rehydrating sibling would re-read a freed operand. "
                "Serving continues uncached; fix the donating jit "
                "wrapper (serve executables must lower through "
                "serve/engine.serve_jit, which never donates)",
                key,
            )
            return None
        try:
            from jax.experimental.serialize_executable import serialize

            blob, in_tree, out_tree = serialize(compiled)
            payload = pickle.dumps(
                (blob, in_tree, out_tree), protocol=pickle.HIGHEST_PROTOCOL
            )
            header = dict(meta)
            header.update(runtime_versions())
            header.update({
                "kind": ENTRY_KIND,
                "version": ENTRY_VERSION,
                "key": str(key),
                "created": round(time.time(), 3),
                "payload_bytes": len(payload),
            })
            hjson = json.dumps(header, sort_keys=True).encode()
            body = len(hjson).to_bytes(8, "big") + hjson + payload
            os.makedirs(self.root, exist_ok=True)
            path = self._path(key)
            tmp = f"{path}.tmp.{os.getpid()}.{next(_TMP_COUNTER)}"
            self._commit(tmp, path, body)
            return path
        except Exception as exc:  # noqa: BLE001 — persist is best-effort
            logger.warning(
                "aot store: failed to persist %s under %s (%s: %s) — "
                "serving continues, this start stays uncached",
                key, self.root, type(exc).__name__, exc,
            )
            return None

    def _commit(self, tmp: str, path: str, body: bytes) -> None:
        """tmp + footer + rename (the checkpoint.py writer idiom); the
        torn-write regression test aborts inside this seam."""
        with open(tmp, "wb") as f:
            f.write(body)
            f.write(_HASH_MAGIC)
            f.write(hashlib.sha256(body).digest())
        os.replace(tmp, path)

    # -- load ----------------------------------------------------------------
    def load(self, key: str, meta: dict):
        """The executable for ``key``, or None. No file = ``miss``; a
        file that is torn, schema-broken, runtime-skewed, or whose
        recorded identity disagrees with ``meta`` = ``skew`` — refused
        with a logged note, never loaded, never a crash. A hit bumps
        the entry's mtime (the ``gc`` LRU clock)."""
        path = self._path(key)
        if not os.path.exists(path):
            self.stats["miss"] += 1
            _note("miss", key)
            return None
        try:
            header, payload = self._read_verified(path)
            reason = self._skew_reason(header, meta)
            if reason is None:
                from jax.experimental.serialize_executable import (
                    deserialize_and_load,
                )

                blob, in_tree, out_tree = pickle.loads(payload)
                with no_xla_compilation_cache():
                    compiled = deserialize_and_load(blob, in_tree, out_tree)
            else:
                raise AOTEntryError(reason)
        except Exception as exc:  # noqa: BLE001 — every failure mode of
            # a cached entry is a refusal-with-note, not a serve outage
            self.stats["skew"] += 1
            logger.warning(
                "aot store: REFUSING cached entry %s (%s: %s) — "
                "recompiling this bucket and re-persisting",
                path, type(exc).__name__, exc,
            )
            _note("skew", key, f"{type(exc).__name__}: {exc}")
            return None
        try:
            os.utime(path, None)
        except OSError:
            pass
        self.stats["hit"] += 1
        _note("hit", key)
        return compiled

    def _read_verified(self, path: str) -> Tuple[dict, bytes]:
        """header + payload, integrity-checked against the sha256
        footer. Any structural problem raises :class:`AOTEntryError`."""
        with open(path, "rb") as f:
            raw = f.read()
        if (
            len(raw) <= _FOOTER_LEN
            or raw[-_FOOTER_LEN:-32] != _HASH_MAGIC
        ):
            raise AOTEntryError("missing integrity footer (torn write?)")
        body, digest = raw[:-_FOOTER_LEN], raw[-32:]
        if hashlib.sha256(body).digest() != digest:
            raise AOTEntryError(
                "content hash mismatch (torn write or bit rot)"
            )
        try:
            hlen = int.from_bytes(body[:8], "big")
            header = json.loads(body[8:8 + hlen].decode())
            payload = body[8 + hlen:]
        except (ValueError, UnicodeDecodeError) as exc:
            raise AOTEntryError(f"unparseable header: {exc}") from exc
        if not isinstance(header, dict):
            raise AOTEntryError("header is not an object")
        return header, payload

    @staticmethod
    def _skew_reason(header: dict, meta: dict) -> Optional[str]:
        """Why this entry must be refused, or None. Checks the entry
        schema, the compiling runtime vs this one, and the recorded key
        identity vs what the caller is about to serve — 'unverifiable'
        must not read as 'verified' (the check_profile rule)."""
        if (
            header.get("kind") != ENTRY_KIND
            or header.get("version") != ENTRY_VERSION
        ):
            return (
                f"entry schema {header.get('kind')!r} "
                f"v{header.get('version')!r} != {ENTRY_KIND!r} "
                f"v{ENTRY_VERSION}"
            )
        here = runtime_versions()
        for field in RUNTIME_FIELDS:
            if header.get(field) != here[field]:
                return (
                    f"compiled under {field}={header.get(field)!r} but "
                    f"this runtime is {field}={here[field]!r}"
                )
        for k, want in meta.items():
            if header.get(k) != want:
                return (
                    f"recorded {k}={header.get(k)!r} != expected "
                    f"{want!r} (key collision or tampered entry)"
                )
        return None

    # -- inspection / eviction ----------------------------------------------
    def ls(self) -> List[dict]:
        """One row per entry (header fields + size/mtime), oldest
        first. Unreadable entries list as ``{"corrupt": True}`` rows —
        ``ls`` is a diagnostic and must not crash on what ``load``
        would refuse."""
        rows: List[dict] = []
        try:
            names = sorted(
                n for n in os.listdir(self.root)
                if n.endswith(ENTRY_SUFFIX)
            )
        except OSError:
            return rows
        for name in names:
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
                header, _ = self._read_verified(path)
                rows.append({
                    **header,
                    "size_bytes": st.st_size,
                    "mtime": st.st_mtime,
                })
            except (OSError, AOTEntryError) as exc:
                rows.append({
                    "key": name[: -len(ENTRY_SUFFIX)],
                    "corrupt": True,
                    "error": str(exc),
                })
        rows.sort(key=lambda r: r.get("mtime", 0.0))
        return rows

    def gc(self, max_bytes: int) -> List[str]:
        """LRU-evict entries (oldest mtime first — hits bump mtime)
        until the store fits ``max_bytes``; returns evicted keys.
        Stale tmp files from killed writers are always swept."""
        evicted: List[str] = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return evicted
        for name in names:
            if ".tmp." in name:
                try:
                    os.unlink(os.path.join(self.root, name))
                except OSError:
                    pass
        entries = []
        total = 0
        for name in names:
            if not name.endswith(ENTRY_SUFFIX):
                continue
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, path, name))
            total += st.st_size
        entries.sort()
        for mtime, size, path, name in entries:
            if total <= max_bytes:
                break
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            key = name[: -len(ENTRY_SUFFIX)]
            evicted.append(key)
            _note("evicted", key)
        return evicted


# -- CLI: python -m distributedpytorch_tpu aot {warm,ls,gc} ------------------
def _require_root(args) -> Optional[str]:
    root = args.aot_cache or os.environ.get(ENV_VAR)
    if not root:
        print(
            "no store directory: pass --aot-cache DIR or set "
            f"${ENV_VAR}", flush=True,
        )
    return root


def _cmd_warm(args) -> int:
    """Prewarm a checkpoint's whole bucket ladder into the store — the
    fleet then cold-starts load-bound. Same identity flags as the serve
    CLI, because the key is the served identity."""
    root = _require_root(args)
    if not root:
        return 2
    from distributedpytorch_tpu.serve.engine import engine_from_checkpoint

    engine = engine_from_checkpoint(
        args.checkpoint,
        checkpoint_dir=args.checkpoint_dir,
        image_size=tuple(args.image_size),
        model_arch=args.model_arch,
        model_widths=(
            tuple(args.model_widths) if args.model_widths else None
        ),
        s2d_levels=args.s2d_levels,
        quantize=args.quantize,
        bucket_sizes=tuple(args.buckets),
        replicas=args.replicas,
        threshold=args.threshold,
        kernels=args.kernels,
        host_cache_mb=0,
        aot_cache=root,
    )
    print(json.dumps({"warmed": engine.aot_cache_stats}, indent=2))
    return 0


def _cmd_ls(args) -> int:
    root = _require_root(args)
    if not root:
        return 2
    rows = AOTStore(root).ls()
    if args.json:
        print(json.dumps(rows, indent=2))
        return 0
    print(f"{len(rows)} entries in {root}")
    for r in rows:
        if r.get("corrupt"):
            print(f"  {r['key']}  CORRUPT: {r.get('error', '')}")
            continue
        shape = "x".join(str(s) for s in r.get("input_shape", []))
        print(
            f"  {r.get('key')}  fp={r.get('engine_fingerprint')}  "
            f"shape={shape}  kernels={r.get('kernels')}  "
            f"dev={r.get('device')}  jaxlib={r.get('jaxlib')}  "
            f"{r.get('size_bytes', 0) / 2**20:.1f} MiB"
        )
    return 0


def _cmd_gc(args) -> int:
    root = _require_root(args)
    if not root:
        return 2
    evicted = AOTStore(root).gc(int(args.max_gb * 2**30))
    print(json.dumps({"evicted": evicted, "max_gb": args.max_gb}))
    return 0


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m distributedpytorch_tpu aot",
        description=(
            "Manage the content-addressed AOT executable store "
            "(docs/PERFORMANCE.md 'AOT executable store')."
        ),
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    warm = sub.add_parser(
        "warm", help="compile-and-persist a checkpoint's bucket ladder"
    )
    warm.add_argument("--checkpoint", "-c", required=True)
    warm.add_argument("--checkpoint-dir", default="./checkpoints")
    warm.add_argument("--image-size", type=int, nargs=2,
                      default=(960, 640), metavar=("W", "H"))
    warm.add_argument("--model", dest="model_arch", default="unet")
    warm.add_argument("--model-widths", type=int, nargs="+", default=None)
    warm.add_argument("--s2d-levels", type=int, default=-1)
    warm.add_argument("--quantize", default=None)
    warm.add_argument("--kernels", default="xla")
    warm.add_argument("--threshold", "-t", type=float, default=0.5)
    warm.add_argument("--buckets", type=int, nargs="+",
                      default=(1, 2, 4, 8))
    warm.add_argument("--replicas", type=int, default=1)
    warm.add_argument("--aot-cache", default=None)
    warm.set_defaults(fn=_cmd_warm)

    ls = sub.add_parser("ls", help="list store entries (oldest first)")
    ls.add_argument("--aot-cache", default=None)
    ls.add_argument("--json", action="store_true")
    ls.set_defaults(fn=_cmd_ls)

    gc = sub.add_parser(
        "gc", help="LRU-evict entries until the store fits --max-gb"
    )
    gc.add_argument("--max-gb", type=float, required=True)
    gc.add_argument("--aot-cache", default=None)
    gc.set_defaults(fn=_cmd_gc)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    import sys

    sys.exit(main())
