from distributedpytorch_tpu.utils.seeding import set_seed  # noqa: F401
