from distributedpytorch_tpu.utils.plotting import plot_img_and_mask  # noqa: F401
from distributedpytorch_tpu.utils.seeding import set_seed  # noqa: F401
