"""Deterministic fault injection + failure-policy primitives.

The resilience subsystem (docs/RELIABILITY.md) has to be *provable* on the
CPU mesh — a recovery path that only ever executes when a real pod flakes
is an untested path. This module provides:

  * a **fault-injection harness**: named sites in the data decode path
    (``decode``, data/loader.py), the placement worker (``placement``,
    utils/prefetch.py), the train-step output (``nan_loss``, train/loop.py),
    the checkpoint writer (``ckpt_write``, checkpoint.py), and a simulated
    preemption (``sigterm``, train/loop.py). Specs are
    ``site:epoch:step[:count]`` strings (``*`` wildcards), armed via
    ``Config.inject_faults`` / CLI ``--inject-fault``, and fire
    deterministically at their (epoch, step) coordinates;
  * the transient-error taxonomy the retry machinery keys on
    (:data:`TRANSIENT_ERRORS`, :func:`call_with_retries` — bounded
    exponential backoff shared by the decode and placement retry paths);
  * :class:`StepWatchdog` — the host-side dispatch watchdog the trainer
    arms per step (train/loop.py);
  * :class:`NonFiniteLossError` — raised by the trainer's non-finite-loss
    policies (``abort`` directly; ``rollback`` after the retry budget).

Installation is process-global and **idempotent per spec list**:
``fit_with_restarts`` rebuilds the Trainer after a crash, and a count-1
fault that already fired must NOT re-arm on the rebuilt attempt — that
would turn every injected crash into an unrecoverable crash loop. Tests
that want a fresh arming call :func:`reset` first.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

from distributedpytorch_tpu.obs import flight

logger = logging.getLogger(__name__)

#: The named injection sites (one per recovery path under test).
#: ``rank_kill`` (SIGKILL this process — the chaos input of the elastic
#: supervisor's detect/relaunch path) and ``rank_hang`` (wedge the step
#: loop in a long sleep — what a dead collective looks like from the
#: host) fire in the step loop (train/loop.py) and are usually pinned to
#: one rank with the ``site@RANK`` spec form.
#:
#: The serve tier's chaos sites (docs/SERVING.md "Fleet & rollout")
#: drill the self-healing paths on CPU: ``serve_dispatch_death`` kills
#: the dispatch loop (→ in-process core relaunch, serve/server.py),
#: ``serve_replica_wedge`` wedges a dispatch in a long sleep (what a
#: hung device call looks like from the host — the supervisor's
#: progress-timeout verdict), ``serve_decode`` fails one request's
#: ingress decode, and ``swap_crash`` fails a weight hot-swap mid-
#: device_put (→ canary rollback, serve/rollout.py). Serve sites carry
#: no epoch; their ``step`` coordinate is the dispatch sequence number.
SITES = (
    "decode", "placement", "nan_loss", "ckpt_write", "sigterm",
    "rank_kill", "rank_hang",
    "serve_dispatch_death", "serve_replica_wedge", "serve_decode",
    "swap_crash",
)


class InjectedFault(Exception):
    """Marker base for every injected failure (testable provenance)."""


class InjectedTransientError(InjectedFault, OSError):
    """An injected *transient* failure (decode / placement): an OSError
    subclass, so the retry paths treat it exactly like the real-world
    transient host I/O errors they exist for."""


class NonFiniteLossError(RuntimeError):
    """A train-step loss came back NaN/Inf and the configured policy
    (``abort``, or ``rollback`` with its budget exhausted) gave up."""


#: What the bounded-backoff retry paths consider transient. OSError covers
#: real host I/O flakes (disk reads, sockets, PIL on torn files) and, via
#: ConnectionError/TimeoutError subclassing, runtime-channel blips; the
#: injected transient error subclasses it deliberately.
TRANSIENT_ERRORS: Tuple[type, ...] = (OSError,)

#: Channel-shaped markers in RuntimeError messages: jaxlib surfaces a
#: flapping runtime channel as XlaRuntimeError (a RuntimeError subclass,
#: NOT an OSError), so the placement retry path must recognize these by
#: message. grpc channel statuses + socket-ish strings only — never
#: 'INTERNAL:' (deterministic compile failures must not retry).
_CHANNEL_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
    "connection", "Connection", "socket", "stream terminated",
)


def is_transient(exc: BaseException) -> bool:
    """True for the failures the bounded-backoff retry paths retry:
    the OSError family, plus channel-shaped RuntimeErrors (how a
    flapping TPU runtime actually surfaces during placement)."""
    if isinstance(exc, TRANSIENT_ERRORS):
        return True
    return isinstance(exc, RuntimeError) and any(
        m in str(exc) for m in _CHANNEL_MARKERS
    )


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: fire at (epoch, step) — None = wildcard — up to
    ``count`` times (-1 = unlimited). ``rank`` pins the fault to one
    process of a multi-process job (None = every rank): how chaos drills
    kill/hang/poison exactly one peer of a live mesh."""

    site: str
    epoch: Optional[int] = None
    step: Optional[int] = None
    count: int = 1
    rank: Optional[int] = None


def _process_index() -> int:
    """This process's rank, lazily (faults.py stays importable without
    jax, and the backend may initialize after specs are armed)."""
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover — jax absent/uninitialized
        return 0


def parse_fault_spec(text: str) -> FaultSpec:
    """Parse ``site[@rank]:epoch:step[:count]``; ``*`` (or omitted)
    wildcards a coordinate; count ``*`` means unlimited; ``@rank`` pins
    the fault to one process (e.g. ``rank_kill@1:1:6``)."""
    parts = str(text).strip().split(":")
    site, rank = parts[0], None
    if "@" in site:
        site, rank_text = site.split("@", 1)
        try:
            rank = int(rank_text)
        except ValueError:
            raise ValueError(
                f"bad fault rank {rank_text!r} in {text!r}: site@RANK"
            ) from None
        if rank < 0:
            raise ValueError(f"fault rank must be >= 0 in {text!r}")
    if site not in SITES:
        raise ValueError(
            f"unknown fault site {site!r}; expected one of {SITES}"
        )

    def coord(i: int) -> Optional[int]:
        if len(parts) <= i or parts[i] in ("", "*"):
            return None
        return int(parts[i])

    if len(parts) > 4:
        raise ValueError(f"bad fault spec {text!r}: site:epoch:step[:count]")
    count = coord(3)
    count = 1 if count is None and (len(parts) <= 3 or parts[3] != "*") else (
        -1 if count is None else count
    )
    if count == 0 or count < -1:
        raise ValueError(f"bad fault count in {text!r} (>=1, or '*')")
    return FaultSpec(
        site=site, epoch=coord(1), step=coord(2), count=count, rank=rank
    )


class FaultInjector:
    """Holds armed :class:`FaultSpec`\\ s; ``fire`` matches + decrements.

    A spec pinned to an epoch/step never matches a call site that cannot
    supply that coordinate (conservative: an unknowable coordinate is not
    a wildcard match) — wildcard the coordinate in the spec instead.
    """

    def __init__(self, specs: Sequence = ()):
        self.raw_specs = tuple(str(s) for s in specs)
        self._specs = [
            s if isinstance(s, FaultSpec) else parse_fault_spec(s)
            for s in specs
        ]
        self._lock = threading.Lock()
        self.fired: Dict[str, int] = {}

    def fire(self, site: str, epoch: Optional[int] = None,
             step: Optional[int] = None) -> bool:
        if not self._specs:  # inert fast path — call sites stay hot-loop safe
            return False
        with self._lock:
            for spec in self._specs:
                if spec.site != site or spec.count == 0:
                    continue
                if spec.rank is not None and spec.rank != _process_index():
                    continue
                if spec.epoch is not None and spec.epoch != epoch:
                    continue
                if spec.step is not None and spec.step != step:
                    continue
                if spec.count > 0:
                    spec.count -= 1
                self.fired[site] = self.fired.get(site, 0) + 1
                logger.warning(
                    "fault injection: firing %r at epoch=%s step=%s",
                    site, epoch, step,
                )
                # the flight recorder's post-mortem tail must show the
                # injected fault next to the phase it killed
                flight.record("fault", site=site, epoch=epoch, step=step)
                return True
        return False


_INERT = FaultInjector(())
_active = _INERT


def install(specs: Sequence) -> FaultInjector:
    """Arm the process-global injector. Idempotent: the same spec tuple
    keeps the CURRENT injector and its decremented counts (see module
    docstring — restart recovery depends on this)."""
    global _active
    raw = tuple(str(s) for s in (specs or ()))
    if raw == _active.raw_specs:
        return _active
    _active = FaultInjector(raw) if raw else _INERT
    return _active


def reset() -> None:
    """Disarm everything (tests)."""
    global _active
    _active = _INERT


def active() -> FaultInjector:
    return _active


def fire(site: str, epoch: Optional[int] = None,
         step: Optional[int] = None) -> bool:
    return _active.fire(site, epoch=epoch, step=step)


def maybe_raise_transient(site: str, epoch: Optional[int] = None,
                          step: Optional[int] = None) -> None:
    if _active.fire(site, epoch=epoch, step=step):
        raise InjectedTransientError(
            f"injected {site} fault (epoch={epoch}, step={step})"
        )


def call_with_retries(
    fn: Callable,
    site: str,
    retries: int,
    backoff_s: float,
    epoch: Optional[int] = None,
    step: Optional[int] = None,
    log: Optional[logging.Logger] = None,
):
    """Run ``fn()`` with up to ``retries`` bounded-exponential-backoff
    retries on :data:`TRANSIENT_ERRORS`, checking the ``site`` injection
    point first each attempt (so an injected transient exercises the SAME
    retry loop a real one would). The final failure re-raises."""
    attempt = 0
    while True:
        try:
            maybe_raise_transient(site, epoch=epoch, step=step)
            return fn()
        except Exception as exc:
            if not is_transient(exc) or attempt >= retries:
                raise
            delay = backoff_s * (2.0 ** attempt)
            from distributedpytorch_tpu.obs import defs as obsm

            obsm.TRAIN_RETRIES.labels(site=site).inc()
            flight.record("retry", site=site, attempt=attempt + 1,
                          error=f"{type(exc).__name__}: {str(exc)[:120]}")
            (log or logger).warning(
                "transient %s failure (attempt %d/%d): %s — retrying in %.2gs",
                site, attempt + 1, retries, exc, delay,
            )
            time.sleep(delay)
            attempt += 1


class StepWatchdog:
    """Host-side dispatch watchdog: flags a step exceeding its timeout.

    The trainer ``pet()``\\ s it once per step-loop iteration and
    ``pause()``\\ s it across the non-step phases (eval, end-of-epoch
    checkpointing) whose legitimate duration is unrelated to step time.
    On expiry, ``on_timeout`` runs ONCE on the watchdog thread (the loop
    thread may be blocked inside a native call — that is the scenario);
    the trainer's callback dumps the step-timeline tracer's spans and
    requests a checkpoint-and-stop through the collective stop agreement
    (train/loop.py). The watchdog disarms after firing — one diagnosis,
    not a spam loop.
    """

    def __init__(self, timeout_s: float, on_timeout: Callable[[], None]):
        self.timeout_s = float(timeout_s)
        self.on_timeout = on_timeout
        self._deadline: Optional[float] = None  # None = paused
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.fired = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dpt-step-watchdog"
        )
        self._thread.start()

    def pet(self) -> None:
        """A step-loop iteration made progress: re-arm the deadline."""
        with self._lock:
            self._deadline = time.monotonic() + self.timeout_s

    def pause(self) -> None:
        with self._lock:
            self._deadline = None

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def _run(self) -> None:
        poll = max(0.01, min(self.timeout_s / 4.0, 0.5))
        while not self._stop.wait(poll):
            with self._lock:
                expired = (
                    not self.fired
                    and self._deadline is not None
                    and time.monotonic() > self._deadline
                )
                if expired:
                    self.fired = True
                    self._deadline = None
            if expired:
                try:
                    self.on_timeout()
                except Exception:  # noqa: BLE001 — diagnostic path only
                    logger.exception("step watchdog callback failed")
