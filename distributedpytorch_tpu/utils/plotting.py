"""Image/mask visualization — the reference's `plot_img_and_mask`
(reference utils/utils.py:38-51) rebuilt for headless TPU hosts.

The reference calls ``plt.show()`` (and is itself never invoked by any repo
code); TPU pods have no display, so the primary mode here is save-to-file.
NHWC divergence: multi-class masks are channels-LAST ``(H, W, C)`` like
everything else in this package (the reference indexes ``mask.shape[0]`` for
the class count but then plots ``mask[:, :, i]`` — channels-last plotting on
a channels-first count, one of its quirks; here both agree).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def plot_img_and_mask(img, mask, out_path: Optional[str] = None):
    """One row of panels: the input image then one panel per mask class.

    `img` is (H, W, 3) [0,1] float or uint8; `mask` is (H, W) or (H, W, C).
    Saves a PNG to `out_path` when given (headless mode), else plt.show().
    Returns the matplotlib figure.
    """
    import matplotlib

    if out_path is not None:
        matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    img = np.asarray(img)
    mask = np.asarray(mask)
    classes = mask.shape[-1] if mask.ndim > 2 else 1
    fig, ax = plt.subplots(1, classes + 1)
    ax[0].set_title("Input image")
    ax[0].imshow(img)
    if classes > 1:
        for i in range(classes):
            ax[i + 1].set_title(f"Output mask (class {i + 1})")
            ax[i + 1].imshow(mask[:, :, i])
    else:
        ax[1].set_title("Output mask")
        ax[1].imshow(mask)
    plt.xticks([])
    plt.yticks([])
    if out_path is not None:
        fig.savefig(out_path, bbox_inches="tight")
        plt.close(fig)
    else:  # pragma: no cover - needs a display
        plt.show()
    return fig
