"""GPipe-style microbatched pipeline over a 'stage' mesh axis, S stages.

TPU-native re-design of the reference's hand-written 2-GPU pipeline
(reference model/unet_model.py:14-53). The reference gets overlap for free
from async CUDA launches: while cuda:1 decodes microbatch i, cuda:0 encodes
microbatch i+1, with the bottleneck + all 4 skip tensors copied cuda:0→cuda:1
each microbatch (unet_model.py:36-37,47-48). On TPU the same schedule is
written explicitly: `shard_map` over a ``stage`` mesh axis, a static loop
over schedule ticks, `lax.cond` selecting each device's stage work, and
`jax.lax.ppermute` carrying inter-stage payloads over ICI.

Generalized from the round-3 two-stage schedule to S stages (VERDICT r03
next-3): the model exposes its linear block order as 2L+1 segments
(models/unet.py `UNet.apply_segment`), a stage is any contiguous run of
segments, and ``cuts`` picks the boundaries. The default for S=2 is the
faithful reference cut (encoder+mid | decoder+head, unet_model.py:16-20);
for S>2 segments are split evenly. Schedule shape: M microbatches over
M + S − 1 ticks — the standard (S−1)-tick warmup/drain bubble, amortized by
raising M.

Skip connections cross stages: encoder segments push skip tensors onto the
carry, decoder segments pop them, so the payload on the edge between stages
s and s+1 is exactly the carry at that cut — bottleneck + not-yet-consumed
skips — and intermediate stages relay the skips their segments don't touch.
Each edge has its own payload shapes; every device materializes every
edge's (zero) buffer, but only the owning stage's is nonzero, and
``lax.cond`` keeps the inactive stage computations unexecuted on TPU.

Differentiation: the whole schedule is a pure function of the (replicated)
params, so `jax.grad` through the `shard_map` gives the pipelined backward
automatically — `ppermute`'s transpose is the reverse permute, so activation
cotangents flow stage s+1 → s with the same overlap structure. Parameters
are replicated across the stage axis (30 MB of params — replication is the
right trade; what is *pipelined* is the activation traffic, which at
(µB,640,960,32) per skip is the dominant term exactly as in the reference).

The ('data', 'stage') hybrid falls out for free: batch sharded over 'data',
schedule over 'stage'; `jax.grad`'s transpose inserts the gradient psum over
'data' — that psum is the DDP all-reduce.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributedpytorch_tpu.utils.compat import shard_map

from distributedpytorch_tpu.ops.losses import bce_dice_stats, loss_from_stats


def default_cuts(num_segments: int, num_stages: int) -> Tuple[int, ...]:
    """Stage boundaries (the segment index each stage s ≥ 1 starts at).

    S=2 reproduces the reference cut — encoder+mid | decoder+head
    (unet_model.py:16-20) — which for 2L+1 segments is the boundary after
    segment L. Other S split the segment list as evenly as possible, with
    the remainder on the LAST stages: the early segments (shallow encoder
    levels) carry most of the FLOPs, and throughput is set by the slowest
    stage, so extra segments belong with the cheap deep/decoder work."""
    if num_stages == 2:
        return ((num_segments - 1) // 2 + 1,)
    base, rem = divmod(num_segments, num_stages)
    sizes = [
        base + (1 if i >= num_stages - rem else 0) for i in range(num_stages)
    ]
    cuts, acc = [], 0
    for size in sizes[:-1]:
        acc += size
        cuts.append(acc)
    return tuple(cuts)


def _stage_ranges(
    num_segments: int, num_stages: int, cuts: Optional[Sequence[int]]
) -> list:
    if num_stages < 1 or num_stages > num_segments:
        raise ValueError(
            f"num_stages {num_stages} out of range for a "
            f"{num_segments}-segment model"
        )
    cuts = tuple(cuts) if cuts is not None else default_cuts(num_segments, num_stages)
    if len(cuts) != num_stages - 1 or list(cuts) != sorted(set(cuts)) or any(
        not 0 < c < num_segments for c in cuts
    ):
        raise ValueError(
            f"cuts {cuts} must be {num_stages - 1} strictly increasing "
            f"segment indices in (0, {num_segments})"
        )
    bounds = (0,) + cuts + (num_segments,)
    return [range(bounds[s], bounds[s + 1]) for s in range(num_stages)]


def _ppermute_edge(tree, axis_name: str, edge: int):
    """Move edge ``edge``'s payload from stage `edge` to stage `edge`+1
    (every other device receives zeros — which is what inactive stages
    should hold)."""
    return jax.tree.map(
        lambda x: jax.lax.ppermute(x, axis_name, perm=[(edge, edge + 1)]), tree
    )


def _zeros_of(template):
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), template)


def _build_stage_fns(model, stage_ranges, remat: bool):
    """One function per stage: chain its segments' (x, skips) → (x, skips)."""

    def seg_apply(params, x, skips, seg):
        return model.apply(
            {"params": params}, x, skips, seg, method=type(model).apply_segment
        )

    fns = []
    for rng in stage_ranges:
        def stage_fn(params, x, skips, _rng=rng):
            for seg in _rng:
                x, skips = seg_apply(params, x, skips, seg)
            return x, skips

        fns.append(jax.checkpoint(stage_fn) if remat else stage_fn)
    return fns


def _run_schedule(stage_fns, M, stage_axis, params, first_input, last_fn,
                  last_zero_fn):
    """Execute the M+S−1-tick GPipe schedule on this device (inside a
    shard_map body); returns the last stage's M outputs in microbatch
    order. ONE definition of the schedule — the loss and forward paths
    differ only in `last_fn` (VERDICT-r03-era duplication removed).

    ``first_input(m) -> (x, skips)`` feeds stage 0 (a microbatch slice);
    ``last_fn(params, payload, m) -> array`` is what the final stage does
    with its stage-input payload; ``last_zero_fn()`` is that output's
    zeros (what every non-final-stage device holds in each slot — summing
    or psumming across the stage axis recovers the real values).
    """
    S = len(stage_fns)
    stage = jax.lax.axis_index(stage_axis)

    # Per-edge payload templates: chain the stage functions over one
    # microbatch's shapes (eval_shape — no FLOPs, no memory).
    def simulate(params):
        x, skips = first_input(0)
        outs = []
        for s in range(S - 1):
            x, skips = stage_fns[s](params, x, skips)
            outs.append((x, skips))
        return tuple(outs)

    templates = jax.eval_shape(simulate, params)
    zero_payloads = [_zeros_of(t) for t in templates]

    outs = []
    in_flight = list(zero_payloads)  # in_flight[e] feeds stage e+1
    for t in range(M + S - 1):
        outgoing = [None] * (S - 1)
        for s in range(S):
            m = t - s  # microbatch stage s handles this tick (static)
            if not 0 <= m < M:
                continue
            payload_in = first_input(m) if s == 0 else in_flight[s - 1]
            if s < S - 1:
                outgoing[s] = jax.lax.cond(
                    stage == s,
                    functools.partial(stage_fns[s], params, *payload_in),
                    lambda _s=s: zero_payloads[_s],
                )
            else:
                outs.append(jax.lax.cond(
                    stage == s,
                    functools.partial(last_fn, params, payload_in, m),
                    last_zero_fn,
                ))
        in_flight = [
            _ppermute_edge(outgoing[e], stage_axis, e)
            if outgoing[e] is not None
            else zero_payloads[e]
            for e in range(S - 1)
        ]
    return outs


def make_pipeline_loss_fn(
    model,
    mesh: Mesh,
    num_microbatches: int = 2,
    stage_axis: str = "stage",
    data_axis: str = None,
    remat: bool = False,
    cuts: Optional[Sequence[int]] = None,
    use_pallas: bool = False,
) -> Callable:
    """Build ``loss_fn(params, batch) -> loss`` running the S-stage GPipe
    schedule over `mesh`'s ``stage`` axis (S = the axis size).

    `batch` is ``{'image': (B,H,W,3) f32, 'mask': (B,H,W,1) f32 target}``
    with B divisible by num_microbatches (× data-axis size when hybrid).
    Returns the same scalar loss as the non-pipelined step: the mean over the
    full batch (microbatches are equal-sized, so mean-of-µmeans == mean).

    `use_pallas` computes each microbatch's loss statistics with the fused
    one-pass Pallas kernel + its analytic VJP (ops/fused_loss.py) — legal
    here because inside the shard_map schedule every array is
    device-local, exactly where pallas_call belongs.
    """
    num_stages = mesh.shape[stage_axis]
    stage_ranges = _stage_ranges(model.num_segments, num_stages, cuts)
    stage_fns = _build_stage_fns(model, stage_ranges, remat)
    M = int(num_microbatches)
    S = num_stages
    if use_pallas:
        from distributedpytorch_tpu.ops.fused_loss import bce_dice_stats_fused

        stats_fn = bce_dice_stats_fused
    else:
        stats_fn = bce_dice_stats

    batch_spec = P(data_axis) if data_axis else P()
    in_specs = (P(), {"image": batch_spec, "mask": batch_spec})
    out_specs = P()

    def per_device(params, batch):
        images = batch["image"]
        masks = batch["mask"]
        if images.shape[0] < M or images.shape[0] % M:
            raise ValueError(
                f"per-shard batch {images.shape[0]} must be a positive "
                f"multiple of num_microbatches={M}"
            )
        mb = images.shape[0] // M  # microbatch size (static)

        def microbatch_input(m):
            return jax.lax.dynamic_slice_in_dim(images, m * mb, mb, axis=0), ()

        def last_stage_stats(params, payload, m):
            x, _skips = stage_fns[S - 1](params, *payload)
            target = jax.lax.dynamic_slice_in_dim(masks, m * mb, mb, axis=0)
            # The log-dice term is a ratio of WHOLE-batch sums (reference
            # utils.py:18-23 computes it on the concatenated pipe output), so
            # microbatches accumulate sufficient statistics, not losses.
            return stats_fn(x, target)

        per_mb_stats = _run_schedule(
            stage_fns, M, stage_axis, params, microbatch_input,
            last_stage_stats, lambda: jnp.zeros((4,), jnp.float32),
        )
        stats_sum = sum(per_mb_stats)
        # Sum stats across the stage axis (only the last stage contributed)
        # and, in the hybrid, across data shards — the result is the EXACT
        # full-global-batch loss, not an average of shard losses.
        axes = (stage_axis, data_axis) if data_axis else (stage_axis,)
        stats = jax.lax.psum(stats_sum, axes)
        return loss_from_stats(stats)

    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )


def make_pipeline_forward_fn(
    model,
    mesh: Mesh,
    num_microbatches: int = 2,
    stage_axis: str = "stage",
    data_axis: str = None,
    cuts: Optional[Sequence[int]] = None,
) -> Callable:
    """Pipelined inference: ``forward(params, images) -> preds``.

    Same schedule as the loss path (literally — `_run_schedule`);
    predictions are psummed across the stage axis so the output is
    replicated over 'stage' (the reference's ``.to('cuda:0')`` gather,
    unet_model.py:53).
    """
    num_stages = mesh.shape[stage_axis]
    stage_ranges = _stage_ranges(model.num_segments, num_stages, cuts)
    stage_fns = _build_stage_fns(model, stage_ranges, remat=False)
    M = int(num_microbatches)
    S = num_stages
    batch_spec = P(data_axis) if data_axis else P()

    def per_device(params, images):
        mb = images.shape[0] // M

        def microbatch_input(m):
            return jax.lax.dynamic_slice_in_dim(images, m * mb, mb, axis=0), ()

        def last_stage_preds(params, payload, m):
            x, _skips = stage_fns[S - 1](params, *payload)
            return x

        out_shape = (mb,) + images.shape[1:3] + (model.n_classes,)
        preds = _run_schedule(
            stage_fns, M, stage_axis, params, microbatch_input,
            last_stage_preds, lambda: jnp.zeros(out_shape, jnp.float32),
        )
        out = jnp.concatenate(preds, axis=0)
        # Replicate across the stage axis: the last stage holds the real
        # output, the rest hold zeros → psum is a broadcast-from-last-stage.
        return jax.lax.psum(out, stage_axis)

    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )
