"""GPipe-style microbatched pipeline over a 'stage' mesh axis.

TPU-native re-design of the reference's hand-written 2-GPU pipeline
(reference model/unet_model.py:14-53). The reference gets overlap for free
from async CUDA launches: while cuda:1 decodes microbatch i, cuda:0 encodes
microbatch i+1, with the bottleneck + all 4 skip tensors copied cuda:0→cuda:1
each microbatch (unet_model.py:36-37,47-48). On TPU the same schedule is
written explicitly: `shard_map` over a ``stage`` mesh axis, a static loop
over schedule ticks, `lax.cond` selecting each device's stage work, and
`jax.lax.ppermute` carrying the bottleneck + skips stage0→stage1 over ICI.

Schedule shape (parity with §3.3 of SURVEY.md): S=2 stages, M microbatches
(default 2, reference hardcodes 2 at unet_model.py:25). Ticks t=0..M: stage 0
encodes microbatch t while stage 1 decodes microbatch t-1 — the classic
1-warmup/1-drain GPipe bubble.

Differentiation: the whole schedule is a pure function of the (replicated)
params, so `jax.grad` through the `shard_map` gives the pipelined backward
automatically — `ppermute`'s transpose is the reverse permute, so activation
cotangents flow stage1→stage0 with the same overlap structure. Parameters are
replicated across the stage axis (30 MB of params — replication is the right
trade; what is *pipelined* is the activation traffic, which at
(µB,640,960,32) per skip is the dominant term exactly as in the reference).
Each device only *executes* its own stage's branch per tick; the inactive
branch of `lax.cond` is not executed on TPU.

The ('data', 'stage') hybrid falls out for free: batch sharded over 'data',
schedule over 'stage'; `jax.grad`'s transpose inserts the gradient psum over
'data' — that psum is the DDP all-reduce.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributedpytorch_tpu.ops.losses import bce_dice_stats, loss_from_stats


def _zeros_like_tree(tree):
    return jax.tree.map(jnp.zeros_like, tree)


def _send_to_next_stage(tree, axis_name: str, num_stages: int):
    """ppermute every leaf stage s → s+1 (last stage's output is dropped)."""
    perm = [(s, s + 1) for s in range(num_stages - 1)]
    return jax.tree.map(
        lambda x: jax.lax.ppermute(x, axis_name, perm=perm), tree
    )


def make_pipeline_loss_fn(
    model,
    mesh: Mesh,
    num_microbatches: int = 2,
    stage_axis: str = "stage",
    data_axis: str = None,
    remat: bool = False,
) -> Callable:
    """Build ``loss_fn(params, batch) -> loss`` running the 2-stage GPipe
    schedule over `mesh`'s ``stage`` axis.

    `batch` is ``{'image': (B,H,W,3) f32, 'mask': (B,H,W,1) f32 target}``
    with B divisible by num_microbatches (× data-axis size when hybrid).
    Returns the same scalar loss as the non-pipelined step: the mean over the
    full batch (microbatches are equal-sized, so mean-of-µmeans == mean).
    """
    num_stages = mesh.shape[stage_axis]
    if num_stages != 2:
        raise ValueError(
            f"2-stage pipeline (reference cut, unet_model.py:16-20); got {num_stages}"
        )
    M = int(num_microbatches)

    encode = model.encode_mid
    decode = model.decode_head
    if remat:
        encode = jax.checkpoint(encode)
        decode = jax.checkpoint(decode)

    batch_spec = P(data_axis) if data_axis else P()
    in_specs = (P(), {"image": batch_spec, "mask": batch_spec})
    out_specs = P()

    def per_device(params, batch):
        stage = jax.lax.axis_index(stage_axis)
        images = batch["image"]
        masks = batch["mask"]
        if images.shape[0] < M or images.shape[0] % M:
            raise ValueError(
                f"per-shard batch {images.shape[0]} must be a positive "
                f"multiple of num_microbatches={M}"
            )
        mb = images.shape[0] // M  # microbatch size (static)

        def encode_mb(t):
            x = jax.lax.dynamic_slice_in_dim(images, t * mb, mb, axis=0)
            bottleneck, skips = model.apply(
                {"params": params}, x, method=encode
            )
            return bottleneck, skips

        # Shape/dtype template for the inter-stage payload.
        template = jax.eval_shape(lambda: encode_mb(0))
        zero_payload = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), template
        )

        def decode_mb(payload, t):
            bottleneck, skips = payload
            preds = model.apply(
                {"params": params}, bottleneck, skips, method=decode
            )
            target = jax.lax.dynamic_slice_in_dim(masks, t * mb, mb, axis=0)
            # The log-dice term is a ratio of WHOLE-batch sums (reference
            # utils.py:18-23 computes it on the concatenated pipe output), so
            # microbatches accumulate sufficient statistics, not losses.
            return bce_dice_stats(preds, target)

        stats_sum = jnp.zeros((4,), jnp.float32)
        in_flight = zero_payload
        for t in range(M + 1):
            # Stage 0 encodes microbatch t (ticks 0..M-1); other stages and
            # drained ticks produce zeros that ppermute discards downstream.
            produce = jnp.logical_and(stage == 0, t < M)
            payload = jax.lax.cond(
                produce,
                lambda: encode_mb(min(t, M - 1)),
                lambda: zero_payload,
            )
            # Stage 1 decodes microbatch t-1 (ticks 1..M) from what arrived
            # last tick.
            consume = jnp.logical_and(stage == num_stages - 1, t >= 1)
            stats_t = jax.lax.cond(
                consume,
                functools.partial(decode_mb, in_flight),
                lambda _unused: jnp.zeros((4,), jnp.float32),
                max(t - 1, 0),
            )
            stats_sum = stats_sum + stats_t
            # Move this tick's product to the next stage for tick t+1.
            in_flight = _send_to_next_stage(payload, stage_axis, num_stages)

        # Sum stats across the stage axis (stage 0 contributed zeros) and,
        # in the hybrid, across data shards — the result is the EXACT
        # full-global-batch loss, not an average of shard losses.
        axes = (stage_axis, data_axis) if data_axis else (stage_axis,)
        stats = jax.lax.psum(stats_sum, axes)
        return loss_from_stats(stats)

    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )


def make_pipeline_forward_fn(
    model,
    mesh: Mesh,
    num_microbatches: int = 2,
    stage_axis: str = "stage",
    data_axis: str = None,
) -> Callable:
    """Pipelined inference: ``forward(params, images) -> preds``.

    Same schedule as the loss path; predictions are ppermuted back to every
    stage so the output is replicated across 'stage' (the reference's
    ``.to('cuda:0')`` gather, unet_model.py:53).
    """
    num_stages = mesh.shape[stage_axis]
    M = int(num_microbatches)
    batch_spec = P(data_axis) if data_axis else P()

    def per_device(params, images):
        stage = jax.lax.axis_index(stage_axis)
        mb = images.shape[0] // M

        def encode_mb(t):
            x = jax.lax.dynamic_slice_in_dim(images, t * mb, mb, axis=0)
            return model.apply({"params": params}, x, method=model.encode_mid)

        template = jax.eval_shape(lambda: encode_mb(0))
        zero_payload = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), template)

        def decode_mb(payload):
            bottleneck, skips = payload
            return model.apply(
                {"params": params}, bottleneck, skips, method=model.decode_head
            )

        out_shape = (mb,) + images.shape[1:3] + (model.n_classes,)
        preds = []
        in_flight = zero_payload
        for t in range(M + 1):
            produce = jnp.logical_and(stage == 0, t < M)
            payload = jax.lax.cond(
                produce, lambda: encode_mb(min(t, M - 1)), lambda: zero_payload
            )
            consume = jnp.logical_and(stage == num_stages - 1, t >= 1)
            pred_t = jax.lax.cond(
                consume,
                functools.partial(decode_mb, in_flight),
                lambda: jnp.zeros(out_shape, jnp.float32),
            )
            if t >= 1:
                preds.append(pred_t)
            in_flight = _send_to_next_stage(payload, stage_axis, num_stages)

        out = jnp.concatenate(preds, axis=0)
        # Replicate across the stage axis: stage 1 holds the real output,
        # stage 0 holds zeros → psum is a broadcast-from-last-stage.
        return jax.lax.psum(out, stage_axis)

    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )
