"""Microbatched pipeline schedules over a 'stage' mesh axis, S stages.

TPU-native re-design of the reference's hand-written 2-GPU pipeline
(reference model/unet_model.py:14-53). The reference gets overlap for free
from async CUDA launches: while cuda:1 decodes microbatch i, cuda:0 encodes
microbatch i+1, with the bottleneck + all 4 skip tensors copied cuda:0→cuda:1
each microbatch (unet_model.py:36-37,47-48). On TPU the same schedule is
written explicitly: `shard_map` over a ``stage`` mesh axis, a static loop
over schedule ticks, `lax.cond` selecting each device's stage work, and
`jax.lax.ppermute` carrying inter-stage payloads over ICI.

Generalized from the round-3 two-stage schedule to S stages (VERDICT r03
next-3): the model exposes its linear block order as 2L+1 segments
(models/unet.py `UNet.apply_segment`, models/milesial.py the same), a stage
is any contiguous run of segments, and ``cuts`` picks the boundaries. The
default for S=2 is the faithful reference cut (encoder+mid | decoder+head,
unet_model.py:16-20); for S>2 segments are split evenly.

Skip connections cross stages: encoder segments push skip tensors onto the
carry, decoder segments pop them, so the payload on the edge between stages
s and s+1 is exactly the carry at that cut — bottleneck + not-yet-consumed
skips — and intermediate stages relay the skips their segments don't touch.
Each edge has its own payload shapes; every device materializes every
edge's (zero) buffer, but only the owning stage's is nonzero, and
``lax.cond`` keeps the inactive stage computations unexecuted on TPU.

Two schedules (``TrainConfig.pipeline_schedule``):

``gpipe`` — fill-drain: M microbatches over M+S−1 forward ticks; the whole
schedule is a pure function of the (replicated) params, so `jax.grad`
through the `shard_map` gives the pipelined backward automatically —
`ppermute`'s transpose is the reverse permute, so activation cotangents
flow stage s+1 → s with the same overlap structure. The price is GPipe's
memory profile (Huang et al., 2019): autodiff saves every microbatch's
stage activations across all M+S−1 ticks, so peak activation memory grows
linearly in M — raising M to amortize the (S−1)-tick bubble is exactly
what runs out of HBM first.

``1f1b`` — PipeDream-flush (Narayanan et al., 2021), built in
`make_pipeline_value_and_grad_fn`: an explicit backward schedule whose
steady-state ticks alternate one-forward-one-backward, holding at most
S−s in-flight microbatches at stage s — peak activation memory is bounded
by S, independent of M, which turns M from a memory liability into a free
throughput lever. Two wrinkles specific to this codebase:

  * The loss is NOT microbatch-additive (the log-dice term is a ratio of
    whole-batch sums, reference utils/utils.py:18-23), so the activation
    cotangent entering ANY backward depends on the psummed whole-batch
    stats — no backward may start before every forward has run. The
    schedule therefore runs two phases inside one shard_map: a
    forward-only stats pass (differentiated by nothing, so XLA frees its
    activations tick by tick), then the 1F1B forward/backward pass
    against the now-known global stats cotangent. The extra forward pass
    is the same price `make_accum_train_step` documents for exact
    accumulation under a non-additive loss.
  * `jax.vjp` residuals are function closures, which cannot cross
    `lax.cond`/`ppermute` as carried state — so the residual carried
    from a stage's forward tick to its backward tick is the stage's
    INPUT payload (the cut carry: bottleneck + pending skips), and the
    backward tick runs `jax.vjp` on the stage from that carry
    (per-stage rematerialization). In-flight state per stage is ≈S−s
    cut carries; the full conv activations exist only transiently
    inside the single backward tick's own VJP.

Per-stage weight gradients accumulate across microbatches in float32 and
one explicit `psum` over ('stage'[, 'data']) closes the hybrid: each
stage's params-gradient leaves are nonzero only for its own segments, so
the stage-psum assembles the full gradient and the data-psum is the DDP
all-reduce (the same reduction `jax.grad`'s transpose inserts for the
gpipe schedule).

BatchNorm threads through both schedules (models/milesial.py): stage
functions take ``(params, batch_stats, x, skips) → ((x, skips),
batch_stats')`` and each stage applies its segments with
``mutable=['batch_stats']`` per microbatch, in microbatch order — GPipe's
published BatchNorm treatment (statistics over each microbatch; running
stats updated per microbatch). Only the owning stage's layers move, so the
final running stats are assembled by psumming each leaf's DELTA across the
stage axis (zeros elsewhere — the stage-axis psum of microbatch moments);
on a hybrid mesh the deltas are additionally pmean'ed over 'data' (each
data replica saw its own shard — torch-DDP-default local-BN semantics,
averaged into one replicated running-stats tree).

Parameters are replicated across the stage axis (30 MB of params —
replication is the right trade; what is *pipelined* is the activation
traffic, which at (µB,640,960,32) per skip is the dominant term exactly as
in the reference).

In-stage sharding (hybrid ``DxMxS`` meshes, ``M>1`` and/or ``@fsdp``):
when the builders receive the strategy's ``mesh_config``, the mesh's
per-tree params rule (mesh.state_leaf_spec — channel-TP over 'model',
ZeRO over 'data') applies INSIDE the stage functions. Params enter the
shard_map sharded per-leaf; the body reconstructs each leaf with ONE
tiled `all_gather` per sharded dim at the top of the step — before any
tick's `lax.cond`, so no collective ever sits inside a stage-gated
branch (which would deadlock the rendezvous and trip the analyzer's
branch-divergent rule). Stage compute then runs on full params, the
per-step gather being the ZeRO-3 trade scaled to the pipeline. The
model axis carries NO schedule collective: replicas along it compute
identically, so the stats/grad/BN psums still close over
('stage'[, 'data']) only — extending them over 'model' would
double-reduce. gpipe's backward needs no new code at all: shard_map's
transpose machinery reduces the per-leaf cotangents back to each
input's own shard layout (the all_gather transposes to a
reduce_scatter), verified grad-exact against the plain step; 1f1b's
explicit f32 accumulators stay full-size per device and each leaf is
sliced back to its own shard after the closing psum, making the grads
output sharded exactly like the params input. A 'spatial' model role
inside a stage is refused loudly (halo exchanges would need to run
inside every tick's cond).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributedpytorch_tpu.utils.compat import shard_map

from distributedpytorch_tpu.ops.losses import bce_dice_stats, loss_from_stats
# The stated f32 contracts (ops/precision.py, docs/PERFORMANCE.md
# "Precision"): loss statistics accumulate in LOSS_DTYPE and per-stage
# weight gradients in WGRAD_DTYPE under EVERY --dtype policy — bf16
# params change what autodiff emits per backward tick, never what this
# schedule accumulates or psums.
from distributedpytorch_tpu.ops.precision import (
    LOSS_DTYPE,
    WGRAD_DTYPE,
    cast_float_leaves,
)

PIPELINE_SCHEDULES = ("gpipe", "1f1b")


def _resolve_data_axis(mesh: Mesh, data_axis):
    """The unified data-axis plumbing: ``"auto"`` (the builders'
    default) derives the hybrid data axis from the mesh itself — a
    'data' axis present means batches shard over it and the stats/grad
    psums close over ('stage', 'data'). Callers no longer thread the
    axis by hand (the strategy layer's mesh config IS the mesh); an
    explicit name or None still overrides for direct API users."""
    if data_axis == "auto":
        return "data" if "data" in mesh.axis_names else None
    return data_axis


def default_cuts(num_segments: int, num_stages: int) -> Tuple[int, ...]:
    """Stage boundaries (the segment index each stage s ≥ 1 starts at).

    S=2 reproduces the reference cut — encoder+mid | decoder+head
    (unet_model.py:16-20) — which for 2L+1 segments is the boundary after
    segment L. Other S split the segment list as evenly as possible, with
    the remainder on the LAST stages: the early segments (shallow encoder
    levels) carry most of the FLOPs, and throughput is set by the slowest
    stage, so extra segments belong with the cheap deep/decoder work."""
    if num_stages == 2:
        return ((num_segments - 1) // 2 + 1,)
    base, rem = divmod(num_segments, num_stages)
    sizes = [
        base + (1 if i >= num_stages - rem else 0) for i in range(num_stages)
    ]
    cuts, acc = [], 0
    for size in sizes[:-1]:
        acc += size
        cuts.append(acc)
    return tuple(cuts)


def _stage_ranges(
    num_segments: int, num_stages: int, cuts: Optional[Sequence[int]]
) -> list:
    if num_stages < 1 or num_stages > num_segments:
        raise ValueError(
            f"num_stages {num_stages} out of range for a "
            f"{num_segments}-segment model"
        )
    cuts = tuple(cuts) if cuts is not None else default_cuts(num_segments, num_stages)
    if len(cuts) != num_stages - 1 or list(cuts) != sorted(set(cuts)) or any(
        not 0 < c < num_segments for c in cuts
    ):
        raise ValueError(
            f"cuts {cuts} must be {num_stages - 1} strictly increasing "
            f"segment indices in (0, {num_segments})"
        )
    bounds = (0,) + cuts + (num_segments,)
    return [range(bounds[s], bounds[s + 1]) for s in range(num_stages)]


def _ppermute_edge(tree, axis_name: str, edge: int, reverse: bool = False):
    """Move edge ``edge``'s payload between stages ``edge`` and ``edge``+1:
    forward activations stage e → e+1, or (``reverse``) activation
    cotangents stage e+1 → e. Every other device receives zeros — which is
    what inactive stages should hold."""
    perm = [(edge + 1, edge)] if reverse else [(edge, edge + 1)]
    return jax.tree.map(
        lambda x: jax.lax.ppermute(x, axis_name, perm=perm), tree
    )


def _zeros_of(template):
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), template)


def _is_stateful(model) -> bool:
    """Models carrying non-trainable collections (BatchNorm running stats)
    — one definition with the plain steps (train/steps.py)."""
    from distributedpytorch_tpu.train.steps import is_stateful_model

    return is_stateful_model(model)


def _merge_stats(full: dict, updates) -> dict:
    """Merge a partial ``batch_stats`` update tree (what a mutable apply of
    ONE segment returns — only that segment's BN layers) into the full
    collection, preserving the full tree's structure so the result can
    cross `lax.cond`/carry boundaries against the unmodified tree."""
    out = dict(full)
    for k, v in updates.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge_stats(out[k], v)
        else:
            out[k] = v
    return out


def _build_stage_fns(model, stage_ranges, remat: bool, train: bool = True):
    """One function per stage: chain its segments' carry → carry.

    Stateless models:  ``stage_fn(params, x, skips) -> (x, skips)``.
    Stateful models:   ``stage_fn(params, bn, x, skips) -> ((x, skips), bn')``
    where ``bn`` is the full batch_stats collection and ``bn'`` merges the
    stage's per-segment updates (train mode; eval applies with the running
    averages and returns ``bn`` unchanged).
    """
    stateful = _is_stateful(model)

    if stateful:
        def seg_apply(params, bn, x, skips, seg):
            variables = {"params": params, "batch_stats": bn}
            if train:
                (x, skips), upd = model.apply(
                    variables, x, skips, seg, True,
                    method=type(model).apply_segment,
                    mutable=["batch_stats"],
                )
                return x, skips, _merge_stats(bn, dict(upd["batch_stats"]))
            x, skips = model.apply(
                variables, x, skips, seg, False,
                method=type(model).apply_segment,
            )
            return x, skips, bn
    else:
        def seg_apply(params, x, skips, seg):
            return model.apply(
                {"params": params}, x, skips, seg,
                method=type(model).apply_segment,
            )

    fns = []
    for rng in stage_ranges:
        if stateful:
            def stage_fn(params, bn, x, skips, _rng=rng):
                for seg in _rng:
                    x, skips, bn = seg_apply(params, bn, x, skips, seg)
                return (x, skips), bn
        else:
            def stage_fn(params, x, skips, _rng=rng):
                for seg in _rng:
                    x, skips = seg_apply(params, x, skips, seg)
                return x, skips

        fns.append(jax.checkpoint(stage_fn) if remat else stage_fn)
    return fns


def _edge_templates(stage_fns, params, bn_state, first_input):
    """Per-edge payload templates: chain the stage functions over one
    microbatch's shapes (eval_shape — no FLOPs, no memory). Edge e's
    template is the carry entering stage e+1."""
    S = len(stage_fns)

    def simulate(params):
        x, skips = first_input(0)
        bn = bn_state
        outs = []
        for s in range(S - 1):
            if bn_state is not None:
                (x, skips), bn = stage_fns[s](params, bn, x, skips)
            else:
                x, skips = stage_fns[s](params, x, skips)
            outs.append((x, skips))
        return tuple(outs)

    return jax.eval_shape(simulate, params)


def _run_schedule(stage_fns, M, stage_axis, params, first_input, last_fn,
                  last_zero_fn, bn_state=None):
    """Execute the M+S−1-tick fill-drain forward schedule on this device
    (inside a shard_map body); returns the last stage's M outputs in
    microbatch order, paired with the device's final batch_stats when
    ``bn_state`` is given. ONE definition of the forward schedule — the
    loss, forward, and 1F1B phase-A paths differ only in `last_fn`.

    ``first_input(m) -> (x, skips)`` feeds stage 0 (a microbatch slice);
    ``last_fn(params, bn, payload, m) -> (out, bn')`` is what the final
    stage does with its stage-input payload; ``last_zero_fn()`` is that
    output's zeros (what every non-final-stage device holds in each slot —
    summing or psumming across the stage axis recovers the real values).
    Stateful stages thread the full batch_stats tree tick to tick; each
    device's tree moves only where its own stage's segments have BN layers.
    """
    S = len(stage_fns)
    stateful = bn_state is not None
    stage = jax.lax.axis_index(stage_axis)

    templates = _edge_templates(stage_fns, params, bn_state, first_input)
    zero_payloads = [_zeros_of(t) for t in templates]

    bn = bn_state
    outs = []
    in_flight = list(zero_payloads)  # in_flight[e] feeds stage e+1
    for t in range(M + S - 1):
        outgoing = [None] * (S - 1)
        for s in range(S):
            m = t - s  # microbatch stage s handles this tick (static)
            if not 0 <= m < M:
                continue
            payload_in = first_input(m) if s == 0 else in_flight[s - 1]
            if s < S - 1:
                if stateful:
                    def work(s=s, payload_in=payload_in, bn=bn):
                        return stage_fns[s](params, bn, *payload_in)

                    outgoing[s], bn = jax.lax.cond(
                        stage == s, work,
                        lambda _s=s, bn=bn: (zero_payloads[_s], bn),
                    )
                else:
                    outgoing[s] = jax.lax.cond(
                        stage == s,
                        functools.partial(stage_fns[s], params, *payload_in),
                        lambda _s=s: zero_payloads[_s],
                    )
            else:
                if stateful:
                    out, bn = jax.lax.cond(
                        stage == s,
                        functools.partial(last_fn, params, bn, payload_in, m),
                        lambda bn=bn: (last_zero_fn(), bn),
                    )
                    outs.append(out)
                else:
                    outs.append(jax.lax.cond(
                        stage == s,
                        functools.partial(last_fn, params, None, payload_in, m),
                        last_zero_fn,
                    ))
        in_flight = [
            _ppermute_edge(outgoing[e], stage_axis, e)
            if outgoing[e] is not None
            else zero_payloads[e]
            for e in range(S - 1)
        ]
    return outs, bn


def _combine_bn(model_state, bn_final, stage_axis, data_axis):
    """Assemble the replicated post-step batch_stats from per-device final
    trees: each leaf moved on exactly ONE stage (zeros-delta elsewhere), so
    psumming the deltas over the stage axis broadcasts every stage's
    updates to all devices; a hybrid mesh additionally pmeans over 'data'
    (each replica normalized its own shard — average the running stats)."""
    def combine(init, fin):
        delta = jax.lax.psum(fin - init, stage_axis)
        if data_axis:
            delta = jax.lax.pmean(delta, data_axis)
        return init + delta

    return jax.tree.map(combine, model_state, bn_final)


def _reduce_grads(grads, axes):
    """Close the schedule: each stage holds only its own segments'
    gradient leaves (zeros elsewhere), so the stage psum assembles the
    full gradient and the 'data' psum is the DDP all-reduce. A named
    seam so the static analyzer's mutation tests (tests/test_analysis.py)
    can drop an axis and prove the comms-contract check catches it."""
    return jax.lax.psum(grads, axes)


def _broadcast_preds(preds, stage_axis):
    """Replicate inference output across the stage axis: the last stage
    holds the real predictions, the rest hold zeros, so the psum is a
    broadcast-from-last-stage (the reference's ``.to('cuda:0')`` gather).
    A named seam (same discipline as ``_reduce_grads``) so the static
    analyzer's mutation tests can drop the eval reduction and prove the
    derived EVAL contract catches stage-local metrics shipping as
    global."""
    return jax.lax.psum(preds, stage_axis)


def _in_stage_config(mesh: Mesh, mesh_config):
    """Gate for in-stage sharding: returns the mesh config when its
    params rule actually shards leaves over an axis this mesh carries
    (channel-TP over the model axis, ZeRO over 'data'), else None — and
    the None path is byte-identical to the pre-hybrid flat schedules
    (replicated params, ``P()`` in_specs). Refuses the spatial model
    role: its halo exchanges would have to run inside every tick's
    stage-gated ``lax.cond``, which the schedule's ppermute program does
    not carry."""
    if mesh_config is None:
        return None
    if mesh_config.model > 1 and mesh_config.model_role == "spatial":
        raise ValueError(
            "pipeline: a 'spatial' model role inside a stage is not "
            "executable — spatial sharding halo-exchanges inside every "
            "schedule tick, which the stage-gated lax.cond program "
            "cannot carry; use the channel role on the model axis "
            "(e.g. '2x2x2') or keep spatial sharding on a flat mesh "
            "(e.g. '2x2x1@sp')"
        )
    model_tp = (
        mesh_config.model > 1
        and mesh_config.model_axis_name in mesh.axis_names
    )
    zero = (
        "fsdp" in mesh_config.params
        and mesh_config.data > 1
        and "data" in mesh.axis_names
    )
    return mesh_config if (model_tp or zero) else None


def _param_spec_tree(cfg, params):
    """Per-leaf in-stage PartitionSpecs from the GLOBAL param shapes —
    the same mesh.state_leaf_spec rule the strategy layer places state
    with, evaluated OUTSIDE the shard_map (a local shard's shape could
    flip a divisibility decision)."""
    from distributedpytorch_tpu.parallel.mesh import state_leaf_spec

    return jax.tree.map(lambda x: state_leaf_spec(cfg, x.shape), params)


def _spec_axes(spec):
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        for name in (entry if isinstance(entry, tuple) else (entry,)):
            yield dim, name


def _gather_params(tree, specs):
    """Reconstruct each leaf's full value from its in-stage shards: one
    tiled `all_gather` per sharded dim, ONCE per step at the top of the
    shard_map body. Replicas along the model axis then compute
    identically, so the schedule's ppermutes/psums need no new axes."""
    def gather(x, spec):
        for dim, name in _spec_axes(spec):
            x = jax.lax.all_gather(x, name, axis=dim, tiled=True)
        return x

    return jax.tree.map(gather, tree, specs)


def _slice_to_shard(tree, specs, axis_sizes):
    """The inverse of `_gather_params` for gradient outputs: 1f1b's f32
    accumulators are full-size per device, so after the closing psum
    each leaf is sliced down to this device's own shard per its spec —
    the grads then leave the shard_map sharded exactly like the params
    entered (out_specs = the same spec tree)."""
    def slice_leaf(x, spec):
        for dim, name in _spec_axes(spec):
            n = int(axis_sizes[name])
            if n == 1:
                continue
            shard = x.shape[dim] // n
            idx = jax.lax.axis_index(name)
            x = jax.lax.dynamic_slice_in_dim(x, idx * shard, shard, axis=dim)
        return x

    return jax.tree.map(slice_leaf, tree, specs)


def _shape_key(tree):
    """Cache key for the lazily-built in-stage shard_maps: the spec
    trees depend only on the global leaf shapes (one model = one key in
    practice; direct API users swapping param shapes get a fresh
    build)."""
    return tuple(tuple(x.shape) for x in jax.tree.leaves(tree))


def _stats_fn(use_pallas: bool):
    if use_pallas:
        from distributedpytorch_tpu.ops.fused_loss import bce_dice_stats_fused

        return bce_dice_stats_fused
    return bce_dice_stats


def _check_microbatching(batch_size: int, M: int) -> int:
    if batch_size < M or batch_size % M:
        raise ValueError(
            f"per-shard batch {batch_size} must be a positive "
            f"multiple of num_microbatches={M}"
        )
    return batch_size // M


def make_pipeline_loss_fn(
    model,
    mesh: Mesh,
    num_microbatches: int = 2,
    stage_axis: str = "stage",
    data_axis: str = "auto",
    remat: bool = False,
    cuts: Optional[Sequence[int]] = None,
    use_pallas: bool = False,
    mesh_config=None,
) -> Callable:
    """Build the fill-drain (gpipe) pipeline loss over `mesh`'s ``stage``
    axis (S = the axis size): ``loss_fn(params, batch) -> loss`` for
    stateless models, ``loss_fn(params, model_state, batch) -> (loss,
    model_state')`` for stateful (BatchNorm) ones — differentiate the
    latter with ``has_aux=True``.

    `batch` is ``{'image': (B,H,W,3) f32, 'mask': (B,H,W,1) f32 target}``
    with B divisible by num_microbatches (× data-axis size when hybrid).
    Returns the same scalar loss as the non-pipelined step: the mean over the
    full batch (microbatches are equal-sized, so mean-of-µmeans == mean).

    `use_pallas` computes each microbatch's loss statistics with the fused
    one-pass Pallas kernel + its analytic VJP (ops/fused_loss.py) — legal
    here because inside the shard_map schedule every array is
    device-local, exactly where pallas_call belongs.

    ``mesh_config`` (the strategy's MeshConfig) engages in-stage param
    sharding on hybrid meshes — see the module docstring; None keeps the
    replicated-params flat path bit-identical.
    """
    in_stage = _in_stage_config(mesh, mesh_config)
    data_axis = _resolve_data_axis(mesh, data_axis)
    num_stages = mesh.shape[stage_axis]
    stage_ranges = _stage_ranges(model.num_segments, num_stages, cuts)
    stage_fns = _build_stage_fns(model, stage_ranges, remat)
    stateful = _is_stateful(model)
    M = int(num_microbatches)
    S = num_stages
    stats_fn = _stats_fn(use_pallas)

    batch_spec = P(data_axis) if data_axis else P()
    axes = (stage_axis, data_axis) if data_axis else (stage_axis,)
    batch_in_spec = {"image": batch_spec, "mask": batch_spec}

    def per_device(params, model_state, batch, specs=None):
        if specs is not None:
            params = _gather_params(params, specs)
        images = batch["image"]
        masks = batch["mask"]
        mb = _check_microbatching(images.shape[0], M)

        def microbatch_input(m):
            return jax.lax.dynamic_slice_in_dim(images, m * mb, mb, axis=0), ()

        def last_stage_stats(params, bn, payload, m):
            if stateful:
                (x, _skips), bn = stage_fns[S - 1](params, bn, *payload)
            else:
                x, _skips = stage_fns[S - 1](params, *payload)
            target = jax.lax.dynamic_slice_in_dim(masks, m * mb, mb, axis=0)
            # The log-dice term is a ratio of WHOLE-batch sums (reference
            # utils.py:18-23 computes it on the concatenated pipe output), so
            # microbatches accumulate sufficient statistics, not losses.
            out = stats_fn(x, target)
            return (out, bn) if stateful else out

        per_mb_stats, bn_final = _run_schedule(
            stage_fns, M, stage_axis, params, microbatch_input,
            last_stage_stats, lambda: jnp.zeros((4,), LOSS_DTYPE),
            bn_state=model_state,
        )
        stats_sum = sum(per_mb_stats)
        # Sum stats across the stage axis (only the last stage contributed)
        # and, in the hybrid, across data shards — the result is the EXACT
        # full-global-batch loss, not an average of shard losses.
        stats = jax.lax.psum(stats_sum, axes)
        loss = loss_from_stats(stats)
        if stateful:
            return loss, _combine_bn(model_state, bn_final, stage_axis, data_axis)
        return loss, None

    if in_stage is None:
        if stateful:
            return shard_map(
                per_device,
                mesh=mesh,
                in_specs=(P(), P(), batch_in_spec),
                out_specs=(P(), P()),
                check_vma=False,
            )
        return shard_map(
            lambda params, batch: per_device(params, None, batch)[0],
            mesh=mesh,
            in_specs=(P(), batch_in_spec),
            out_specs=P(),
            check_vma=False,
        )

    # in-stage sharding: the per-leaf spec tree depends on the GLOBAL
    # param shapes, so the shard_map is built lazily at first call (and
    # cached per shape signature — one model, one build)
    cache = {}

    def _built(params):
        key = _shape_key(params)
        fn = cache.get(key)
        if fn is None:
            specs = _param_spec_tree(in_stage, params)
            if stateful:
                fn = shard_map(
                    functools.partial(per_device, specs=specs),
                    mesh=mesh,
                    in_specs=(specs, P(), batch_in_spec),
                    out_specs=(P(), P()),
                    check_vma=False,
                )
            else:
                fn = shard_map(
                    lambda p, b: per_device(p, None, b, specs=specs)[0],
                    mesh=mesh,
                    in_specs=(specs, batch_in_spec),
                    out_specs=P(),
                    check_vma=False,
                )
            cache[key] = fn
        return fn

    if stateful:
        def loss_fn(params, model_state, batch):
            return _built(params)(params, model_state, batch)
    else:
        def loss_fn(params, batch):
            return _built(params)(params, batch)
    return loss_fn


def make_pipeline_value_and_grad_fn(
    model,
    mesh: Mesh,
    num_microbatches: int = 2,
    stage_axis: str = "stage",
    data_axis: str = "auto",
    remat: bool = False,
    cuts: Optional[Sequence[int]] = None,
    use_pallas: bool = False,
    schedule: str = "1f1b",
    mesh_config=None,
) -> Callable:
    """Build ``f(params, model_state, batch) -> (loss, grads, model_state')``
    for either pipeline schedule (``model_state`` is None for stateless
    models and passed through unchanged).

    ``schedule='gpipe'`` differentiates the fill-drain loss with
    `jax.value_and_grad` (activation memory grows with M).
    ``schedule='1f1b'`` runs the explicit PipeDream-flush schedule built
    here: phase A is the forward-only stats pass (fill-drain, nothing
    saved), phase B alternates one-forward-one-backward per stage over
    2(M+S−1) ticks — forward of microbatch m at stage s on tick s+2m,
    backward on tick 2S−1−s+2m, so stage s holds at most ≈S−s in-flight
    input carries and the bubble matches gpipe's. Each backward tick runs
    `jax.vjp` on the stage's segment run from the saved input carry
    against the incoming activation cotangent (the global stats cotangent
    at the last stage); cotangents flow stage s+1 → s over the reverse
    `ppermute`, and per-stage weight gradients accumulate in float32
    before one psum over ('stage'[, 'data']) closes DDP_MP. See the
    module docstring for why the loss's non-additivity forces phase A and
    why the carried residual is the input carry.
    """
    if schedule not in PIPELINE_SCHEDULES:
        raise ValueError(
            f"pipeline schedule must be one of {PIPELINE_SCHEDULES}, "
            f"got {schedule!r}"
        )
    in_stage = _in_stage_config(mesh, mesh_config)
    data_axis = _resolve_data_axis(mesh, data_axis)
    stateful = _is_stateful(model)

    if schedule == "gpipe":
        loss_fn = make_pipeline_loss_fn(
            model, mesh, num_microbatches=num_microbatches,
            stage_axis=stage_axis, data_axis=data_axis, remat=remat,
            cuts=cuts, use_pallas=use_pallas, mesh_config=mesh_config,
        )

        def _wide(params):
            # REDUCE_DTYPE contract: differentiate w.r.t. an f32 view of
            # the params so autodiff's cotangents — and the implicit
            # schedule-closing psum the shard_map transpose inserts over
            # ('stage'[,'data']) — reduce in f32 even when the --dtype
            # policy stores bf16 params (bf16→f32 is exact; the model
            # re-casts to its compute dtype immediately, so the forward
            # is unchanged; a no-op for f32 params).
            return cast_float_leaves(params, WGRAD_DTYPE)

        if stateful:
            def gpipe_vag(params, model_state, batch):
                (loss, new_state), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(_wide(params), model_state, batch)
                return loss, grads, new_state
        else:
            def gpipe_vag(params, model_state, batch):
                loss, grads = jax.value_and_grad(loss_fn)(_wide(params), batch)
                return loss, grads, model_state
        return gpipe_vag

    num_stages = mesh.shape[stage_axis]
    stage_ranges = _stage_ranges(model.num_segments, num_stages, cuts)
    stage_fns = _build_stage_fns(model, stage_ranges, remat)
    M = int(num_microbatches)
    S = num_stages
    stats_fn = _stats_fn(use_pallas)

    batch_spec = P(data_axis) if data_axis else P()
    axes = (stage_axis, data_axis) if data_axis else (stage_axis,)
    batch_in_spec = {"image": batch_spec, "mask": batch_spec}

    def per_device(params, model_state, batch, specs=None):
        if specs is not None:
            params = _gather_params(params, specs)
        images = batch["image"]
        masks = batch["mask"]
        mb = _check_microbatching(images.shape[0], M)
        stage = jax.lax.axis_index(stage_axis)

        def microbatch_input(m):
            return jax.lax.dynamic_slice_in_dim(images, m * mb, mb, axis=0), ()

        def target(m):
            return jax.lax.dynamic_slice_in_dim(masks, m * mb, mb, axis=0)

        def fwd_stage(s, payload):
            """Stage forward for phase B (BN in train mode, updates
            discarded: phase A already accumulated them, and the
            normalization itself reads only the microbatch moments)."""
            if stateful:
                out, _bn = stage_fns[s](params, model_state, *payload)
                return out
            return stage_fns[s](params, *payload)

        # ---- phase A: forward-only fill-drain — global loss stats (and
        # BatchNorm running-stat updates); NOT differentiated, so XLA
        # frees each tick's activations as soon as the edge payload ships.
        def last_stage_stats(params, bn, payload, m):
            if stateful:
                (x, _skips), bn = stage_fns[S - 1](params, bn, *payload)
                return stats_fn(x, target(m)), bn
            x, _skips = stage_fns[S - 1](params, *payload)
            return stats_fn(x, target(m))

        per_mb_stats, bn_final = _run_schedule(
            stage_fns, M, stage_axis, params, microbatch_input,
            last_stage_stats, lambda: jnp.zeros((4,), LOSS_DTYPE),
            bn_state=model_state if stateful else None,
        )
        stats = jax.lax.psum(sum(per_mb_stats), axes)
        loss = loss_from_stats(stats)
        # the 4-vector every backward needs: ∇loss at the GLOBAL stats
        ct_stats = jax.grad(loss_from_stats)(stats)
        new_model_state = (
            _combine_bn(model_state, bn_final, stage_axis, data_axis)
            if stateful else model_state
        )

        # ---- phase B: 1F1B — forward of (s, m) at tick s+2m, backward at
        # tick 2S−1−s+2m. Forward and backward tick sets of one stage have
        # opposite parities, so each stage does at most one unit per tick;
        # the last stage's "forward" tick only banks the arriving carry
        # (its compute happens inside the backward tick's VJP).
        templates = _edge_templates(
            stage_fns, params, model_state if stateful else None,
            microbatch_input,
        )
        zero_payloads = [_zeros_of(t) for t in templates]
        zero_mb_input = _zeros_of(
            jax.eval_shape(lambda p: microbatch_input(0), params)
        )
        grad_zero = jax.tree.map(
            lambda x: jnp.zeros(x.shape, WGRAD_DTYPE), params
        )
        grads = grad_zero
        saved = {}  # (s, m) -> stage input carry, live ≈S−s ticks
        fwd_edge = list(zero_payloads)  # fwd_edge[e] feeds stage e+1
        bwd_edge = list(zero_payloads)  # bwd_edge[e]: cot of stage e's output
        for t in range(2 * M + 2 * S - 2):
            out_fwd = [None] * (S - 1)
            out_bwd = [None] * (S - 1)
            for s in range(S):
                m_f, r_f = divmod(t - s, 2)
                if r_f == 0 and 0 <= m_f < M:  # forward unit
                    payload_in = (
                        microbatch_input(m_f) if s == 0 else fwd_edge[s - 1]
                    )
                    saved[(s, m_f)] = payload_in
                    if s < S - 1:
                        out_fwd[s] = jax.lax.cond(
                            stage == s,
                            functools.partial(fwd_stage, s, payload_in),
                            lambda _s=s: zero_payloads[_s],
                        )
                m_b, r_b = divmod(t - (2 * S - 1 - s), 2)
                if r_b == 0 and 0 <= m_b < M:  # backward unit
                    payload_in = saved.pop((s, m_b))
                    ct_in = ct_stats if s == S - 1 else bwd_edge[s]

                    # the f32 grad accumulation lives INSIDE the cond:
                    # the inactive branch passes the running tree through
                    # untouched, so only the owning stage's device pays a
                    # full-param-tree add per backward unit (M adds per
                    # device per step, not M·S-with-zeros)
                    def bwd_work(s=s, m=m_b, payload_in=payload_in,
                                 ct_in=ct_in, grads=grads):
                        if s == S - 1:
                            def f(p, payload):
                                if stateful:
                                    (y, _sk), _bn = stage_fns[s](
                                        p, model_state, *payload
                                    )
                                else:
                                    y, _sk = stage_fns[s](p, *payload)
                                return stats_fn(y, target(m))
                        else:
                            def f(p, payload):
                                if stateful:
                                    out, _bn = stage_fns[s](
                                        p, model_state, *payload
                                    )
                                    return out
                                return stage_fns[s](p, *payload)

                        _, vjp = jax.vjp(f, params, payload_in)
                        g_params, g_payload = vjp(ct_in)
                        acc = jax.tree.map(
                            lambda a, g: a + g.astype(WGRAD_DTYPE),
                            grads, g_params,
                        )
                        return acc, g_payload

                    zero_in = zero_mb_input if s == 0 else zero_payloads[s - 1]
                    grads, g_payload = jax.lax.cond(
                        stage == s, bwd_work,
                        lambda g=grads, z=zero_in: (g, z),
                    )
                    if s > 0:
                        out_bwd[s - 1] = g_payload
            fwd_edge = [
                _ppermute_edge(out_fwd[e], stage_axis, e)
                if out_fwd[e] is not None else zero_payloads[e]
                for e in range(S - 1)
            ]
            bwd_edge = [
                _ppermute_edge(out_bwd[e], stage_axis, e, reverse=True)
                if out_bwd[e] is not None else zero_payloads[e]
                for e in range(S - 1)
            ]
        grads = _reduce_grads(grads, axes)
        if specs is not None:
            # the model axis carried no reduction (its replicas'
            # accumulators are identical); slice each full leaf down to
            # this device's own shard so the grads leave the shard_map
            # laid out exactly like the params entered
            grads = _slice_to_shard(grads, specs, dict(mesh.shape))
        return loss, grads, new_model_state

    if in_stage is None:
        if stateful:
            return shard_map(
                per_device,
                mesh=mesh,
                in_specs=(P(), P(), batch_in_spec),
                out_specs=(P(), P(), P()),
                check_vma=False,
            )

        sharded = shard_map(
            lambda params, batch: per_device(params, None, batch)[:2],
            mesh=mesh,
            in_specs=(P(), batch_in_spec),
            out_specs=(P(), P()),
            check_vma=False,
        )

        def stateless_vag(params, model_state, batch):
            loss, grads = sharded(params, batch)
            return loss, grads, model_state

        return stateless_vag

    # in-stage sharding: lazily built per global param shapes (the spec
    # tree is both the params in_spec and the grads out_spec)
    cache = {}

    def _built(params):
        key = _shape_key(params)
        fn = cache.get(key)
        if fn is None:
            specs = _param_spec_tree(in_stage, params)
            if stateful:
                fn = shard_map(
                    functools.partial(per_device, specs=specs),
                    mesh=mesh,
                    in_specs=(specs, P(), batch_in_spec),
                    out_specs=(P(), specs, P()),
                    check_vma=False,
                )
            else:
                fn = shard_map(
                    lambda p, b: per_device(p, None, b, specs=specs)[:2],
                    mesh=mesh,
                    in_specs=(specs, batch_in_spec),
                    out_specs=(P(), specs),
                    check_vma=False,
                )
            cache[key] = fn
        return fn

    if stateful:
        def sharded_vag(params, model_state, batch):
            return _built(params)(params, model_state, batch)
    else:
        def sharded_vag(params, model_state, batch):
            loss, grads = _built(params)(params, batch)
            return loss, grads, model_state

    return sharded_vag


def make_pipeline_forward_fn(
    model,
    mesh: Mesh,
    num_microbatches: int = 2,
    stage_axis: str = "stage",
    data_axis: str = "auto",
    cuts: Optional[Sequence[int]] = None,
    mesh_config=None,
) -> Callable:
    """Pipelined inference: ``forward(variables, images) -> preds``.

    ``variables`` is the bare params tree for stateless models, or the
    full ``{'params', 'batch_stats'}`` dict for stateful ones (running
    averages; nothing mutates). Same fill-drain schedule as the loss path
    (literally — `_run_schedule`); predictions are psummed across the
    stage axis so the output is replicated over 'stage' (the reference's
    ``.to('cuda:0')`` gather, unet_model.py:53).
    """
    in_stage = _in_stage_config(mesh, mesh_config)
    data_axis = _resolve_data_axis(mesh, data_axis)
    num_stages = mesh.shape[stage_axis]
    stage_ranges = _stage_ranges(model.num_segments, num_stages, cuts)
    stateful = _is_stateful(model)
    stage_fns = _build_stage_fns(model, stage_ranges, remat=False, train=False)
    M = int(num_microbatches)
    S = num_stages
    batch_spec = P(data_axis) if data_axis else P()

    def per_device(variables, images, specs=None):
        if stateful:
            params = variables["params"]
            bn = variables["batch_stats"]
        else:
            params, bn = variables, None
        if specs is not None:
            params = _gather_params(params, specs)
        # same guard as the train paths: a ragged batch would silently
        # floor to mb=0 (empty predictions) or drop samples here
        mb = _check_microbatching(images.shape[0], M)

        def microbatch_input(m):
            return jax.lax.dynamic_slice_in_dim(images, m * mb, mb, axis=0), ()

        def last_stage_preds(params, bn_in, payload, m):
            if stateful:
                (x, _skips), bn_in = stage_fns[S - 1](params, bn_in, *payload)
                return x, bn_in
            x, _skips = stage_fns[S - 1](params, *payload)
            return x

        out_shape = (mb,) + images.shape[1:3] + (model.n_classes,)
        preds, _ = _run_schedule(
            stage_fns, M, stage_axis, params, microbatch_input,
            last_stage_preds, lambda: jnp.zeros(out_shape, LOSS_DTYPE),
            bn_state=bn,
        )
        out = jnp.concatenate(preds, axis=0)
        return _broadcast_preds(out, stage_axis)

    if in_stage is None:
        return shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), batch_spec),
            out_specs=batch_spec,
            check_vma=False,
        )

    # in-stage sharding: params enter per-leaf sharded (batch_stats, for
    # stateful models, stay replicated — the running averages are read
    # whole by every stage)
    cache = {}

    def forward(variables, images):
        params = variables["params"] if stateful else variables
        key = _shape_key(params)
        fn = cache.get(key)
        if fn is None:
            specs = _param_spec_tree(in_stage, params)
            var_spec = {"params": specs, "batch_stats": P()} if stateful else specs
            fn = shard_map(
                functools.partial(per_device, specs=specs),
                mesh=mesh,
                in_specs=(var_spec, batch_spec),
                out_specs=batch_spec,
                check_vma=False,
            )
            cache[key] = fn
        return fn(variables, images)

    return forward
