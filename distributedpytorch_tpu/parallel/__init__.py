"""Parallelism over a `jax.sharding.Mesh`: the composable mesh engine.

Capability parity with the reference's four execution modes plus every
hybrid (SURVEY.md §2 checklist), all expressed as points in one N-D
``('data', 'model', 'stage')`` mesh space with per-tree sharding rules
(``parallel/mesh.py``): single device, DP/DDP (data axis), MP (stage
axis, reference unet_model.py:14-53), SP/TP (the model axis's spatial /
channel roles), FSDP (the ``fsdp`` params rule), the named hybrids
(DDP_MP, DDP_SP), and arbitrary ``-t DxMxS[@rule]`` mesh specs — mesh +
shardings + collectives, not NCCL/CUDA streams.

Lazily re-exported (PEP 562): ``parallel.mesh`` is the jax-free rules
module — the dptlint contract derivation, the planner's plan-file path,
and the elastic supervisor import it, and a plain ``from
distributedpytorch_tpu.parallel.mesh import ...`` must not drag the
strategy layer's jax import in through this package ``__init__``.
"""

import importlib

_EXPORTS = {
    "STRATEGIES": ".strategy",
    "DataParallel": ".strategy",
    "DistributedDataParallel": ".strategy",
    "FullyShardedDataParallel": ".strategy",
    "GenericMesh": ".strategy",
    "HybridDataPipeline": ".strategy",
    "HybridDataSpatial": ".strategy",
    "Pipeline": ".strategy",
    "SingleDevice": ".strategy",
    "SpatialParallel": ".strategy",
    "Strategy": ".strategy",
    "TensorParallel": ".strategy",
    "build_strategy": ".strategy",
    "PIPELINE_SCHEDULES": ".pipeline",
    "make_pipeline_forward_fn": ".pipeline",
    "make_pipeline_loss_fn": ".pipeline",
    "make_pipeline_value_and_grad_fn": ".pipeline",
    "MeshConfig": ".mesh",
    "canonical_spec": ".mesh",
    "is_mesh_spec": ".mesh",
    "parse_mesh_spec": ".mesh",
    "spec_is_pipeline": ".mesh",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    module_name = _EXPORTS.get(name)
    if module_name is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    module = importlib.import_module(module_name, __name__)
    return getattr(module, name)
