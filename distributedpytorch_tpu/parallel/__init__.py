"""Parallelism strategies over a `jax.sharding.Mesh`.

Capability parity with the reference's four execution modes plus the hybrid
(SURVEY.md §2 checklist): single device, DP (single-process data parallel,
reference train_utils.py:98), DDP (multi-process data parallel with gradient
all-reduce, train_utils.py:170-248), MP (2-stage microbatched pipeline,
unet_model.py:14-53), and DDP×MP on a 2-D ('data', 'stage') mesh — expressed
as mesh + shardings + collectives, not NCCL/CUDA streams.
"""

from distributedpytorch_tpu.parallel.strategy import (  # noqa: F401
    STRATEGIES,
    DataParallel,
    DistributedDataParallel,
    HybridDataPipeline,
    Pipeline,
    SingleDevice,
    Strategy,
    build_strategy,
)
from distributedpytorch_tpu.parallel.pipeline import (  # noqa: F401
    PIPELINE_SCHEDULES,
    make_pipeline_loss_fn,
    make_pipeline_value_and_grad_fn,
)
