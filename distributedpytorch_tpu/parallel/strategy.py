"""Strategy objects: one mesh-rule engine, strategies as named points.

ONE trainer (train/loop.py) consumes these; a strategy answers: which
mesh, how batches are placed/sharded, how the train step is jitted,
which process does eval/checkpoint/metrics, how the dataloader is
sharded, and how the lr scales — everything that differed between the
reference's three copy-pasted ``fit*`` loops (SURVEY.md §2).

Since the composable-mesh refactor there is exactly ONE set of step /
eval / placement builders, living on :class:`Strategy` and driven by a
:class:`~distributedpytorch_tpu.parallel.mesh.MeshConfig` (the N-D
``('data', 'model', 'stage')`` mesh + per-tree sharding rules —
parallel/mesh.py). Each legacy ``-t`` name is a thin subclass whose
only job is resolving its named point against the device pool
(`_mesh_layout`); arbitrary points launch as ``-t DxMxS[@rule]`` mesh
specs through :class:`GenericMesh` — including hybrids the old
class-per-strategy design could not express (``2x2x1`` = DP x TP,
``2x2x1@fsdp`` = FSDP x TP).

Method-name parity with the reference CLI (reference train.py:17,
:46-64): ``singleGPU``, ``DP``, ``DDP``, ``MP``, plus the additive
``DDP_MP``/``SP``/``DDP_SP``/``TP``/``FSDP`` and the mesh specs.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributedpytorch_tpu.config import TrainConfig
from distributedpytorch_tpu.data.loader import ShardSpec
from distributedpytorch_tpu.ops.precision import get_policy
from distributedpytorch_tpu.parallel import mesh as mesh_rules
from distributedpytorch_tpu.parallel.mesh import MeshConfig
from distributedpytorch_tpu.parallel.pipeline import (
    PIPELINE_SCHEDULES,
    make_pipeline_forward_fn,
    make_pipeline_value_and_grad_fn,
)
from distributedpytorch_tpu.train.steps import (
    TrainState,
    grouped_eval_metrics,
    make_accum_train_step,
    make_eval_step,
    make_multi_train_step,
    make_train_step,
)


def _prep_mask(mask: jax.Array) -> jax.Array:
    return mask[..., None].astype(jnp.float32)


def _validate_pipeline_schedule(config: TrainConfig) -> None:
    """Fail at strategy CONSTRUCTION (before model build / data setup)
    on an unknown schedule; the pipeline builder itself re-checks for
    direct API users."""
    if config.pipeline_schedule not in PIPELINE_SCHEDULES:
        raise ValueError(
            f"pipeline_schedule must be one of {PIPELINE_SCHEDULES}, "
            f"got {config.pipeline_schedule!r}"
        )


def _state_donation(config: Optional[TrainConfig] = None) -> tuple:
    """``donate_argnums`` for the jitted train steps: donating the state
    halves HBM pressure on accelerators (in-place Adam update), but the
    jax 0.4.37 CPU client intermittently ABORTS (native SIGABRT/SIGSEGV,
    no Python traceback) when donated executables from sequentially-built
    trainers run in one process — reproduced at ~40-50% on the restart
    tests (two Trainers per process) and ~10% on a plain resume, 0/15
    with donation off, seed code either way. CPU donation saves nothing
    (buffers are host RAM regardless), so donate only off-CPU.

    ``nonfinite_policy='skip'`` also disables donation everywhere: the
    trainer holds the PREVIOUS state across each step so a non-finite
    step's update can be discarded — a donated previous state would be
    deleted buffers (train/loop.py)."""
    if config is not None and config.nonfinite_policy == "skip":
        return ()
    return () if jax.default_backend() == "cpu" else (0,)


def _shrunk_data_degree(name: str, batch_size: int, n_devices: int) -> int:
    """Largest data degree <= n_devices dividing the batch, warning
    loudly when devices are left idle (torch DataParallel would scatter
    unevenly instead; GSPMD needs the batch to divide the mesh —
    VERDICT r03 missing-3)."""
    n = n_devices
    while batch_size % n:
        n -= 1
    if n != n_devices:
        import logging

        logging.getLogger(__name__).warning(
            "%s: batch size %d does not divide the %d available devices "
            "— data mesh shrunk to %d device(s); %d idle. torch "
            "DataParallel would scatter unevenly instead; here the "
            "batch must divide the mesh. Use a batch size divisible by "
            "the device count to engage every device.",
            name, batch_size, n_devices, n, n_devices - n,
        )
    return n


class Strategy:
    """Base: the mesh-rule engine. Every step/eval/placement builder
    lives HERE, driven by ``self.mesh_config``; subclasses only resolve
    their named point (`_mesh_layout`). The base itself is the no-mesh
    single-device point."""

    name = "base"

    def __init__(self, config: TrainConfig, devices=None):
        self.config = config
        # the session's precision policy (ops/precision.py, --dtype):
        # resolved ONCE here; the steps this strategy builds, the
        # checkpoint manifest, and the restore path all read this object
        self.policy = get_policy(config)
        # the kernel-engagement policy (ops/kernels.py, --kernels):
        # resolved ONCE with the Mosaic probe priors applied (the legacy
        # use_pallas flag resolves inside, as a loud alias)
        from distributedpytorch_tpu.ops.kernels import get_kernel_policy

        self.kernels = get_kernel_policy(config)
        # the mesh point this strategy IS: axis sizes + sharding rules
        self.mesh_config, devs = self._mesh_layout(config, devices)
        self.mesh: Optional[Mesh] = mesh_rules.build_mesh(
            self.mesh_config, devs
        )
        self.batch_sharding: Optional[NamedSharding] = (
            None if self.mesh is None
            else NamedSharding(
                self.mesh, mesh_rules.batch_partition_spec(self.mesh_config)
            )
        )

    # -- the named point ----------------------------------------------------
    def _mesh_layout(
        self, config: TrainConfig, devices
    ) -> Tuple[MeshConfig, Sequence]:
        """(MeshConfig, device pool) for this strategy — the ONLY thing
        a legacy strategy class defines. Base: the 1x1x1 point."""
        return MeshConfig(), ()

    @property
    def is_pipeline(self) -> bool:
        return self.mesh_config.is_pipeline

    @property
    def pipeline_data_axis(self) -> Optional[str]:
        return "data" if self.mesh_config.data > 1 else None

    # -- process topology ---------------------------------------------------
    @property
    def is_main(self) -> bool:
        """Rank-0 gating for eval/checkpoint/metrics (reference
        train_utils.py:229-248). Single-process strategies: always True."""
        return jax.process_index() == 0

    def data_shard(self) -> ShardSpec:
        """How the dataloader shards samples across processes
        (DistributedSampler parity, reference train_utils.py:189)."""
        return ShardSpec(0, 1)

    def topology(self) -> Dict[str, Any]:
        """This strategy's mesh/process topology, as recorded in the
        checkpoint manifest (checkpoint.save_topology fills the process/
        device counts): the saving side of the mesh-resharding restore.
        Keys are msgpack-plain (str → str/int)."""
        mesh = (
            {}
            if self.mesh is None
            else {str(k): int(v) for k, v in self.mesh.shape.items()}
        )
        # "precision" is the ckpt-dtype-drift contract's anchor: restore
        # compares it against the session policy and converts/re-casts
        # loudly instead of silently retracing (train/loop._restore).
        # "mesh_spec" is the canonical mesh-point name — an N→M
        # mesh-resharding restore logs the TRUE source geometry, not
        # just the (possibly aliased) legacy strategy name.
        return {
            "strategy": self.name,
            "mesh": mesh,
            "mesh_spec": mesh_rules.canonical_spec(self.mesh_config),
            "precision": self.policy.name,
        }

    # -- batch semantics ----------------------------------------------------
    @property
    def global_batch_size(self) -> int:
        """config.batch_size is the per-process batch (torch DataLoader
        semantics); single-process strategies: global == local."""
        return self.config.batch_size

    @property
    def drop_last_train(self) -> bool:
        return self.mesh_config.drop_last

    def lr_for(self, base_lr: float) -> float:
        return base_lr

    # -- placement ----------------------------------------------------------
    def place_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        if self.mesh is None:
            dev = jax.devices()[0]
            return {k: jax.device_put(v, dev) for k, v in batch.items()}
        return {
            k: jax.device_put(v, self.batch_sharding) for k, v in batch.items()
        }

    def place_state(self, state: TrainState) -> TrainState:
        if self.mesh is None:
            dev = jax.devices()[0]
            return jax.device_put(state, dev)
        if self.mesh_config.params == "replicate":
            return _replicate(self.mesh, state)
        placed = _shard_state_by_rule(
            state, self.mesh, self._leaf_spec, self.name
        )
        if self.is_pipeline and state.model_state is not None:
            # the pipeline schedules read batch_stats whole on every
            # stage (in_specs P()); placing it sharded would force a
            # gather-then-resharding recompile on the second step
            placed = TrainState(
                params=placed.params,
                opt_state=placed.opt_state,
                step=placed.step,
                model_state=_replicate(self.mesh, state.model_state),
            )
        return placed

    def _leaf_spec(self, shape) -> P:
        """The per-tree params/opt-state rule — one definition
        (mesh.state_leaf_spec) shared by placement here and the
        analyzer/planner's AOT sharding pins
        (analysis/collectives.compile_train_step_aot)."""
        return mesh_rules.state_leaf_spec(self.mesh_config, shape)

    def place_work(self, kind: str, payload):
        """The async step pipeline's H2D entry (utils/prefetch.
        pipelined_placement): one call placing either work-item kind, so
        the placement worker needs no strategy knowledge. ``'single'`` is
        a per-step host batch (→ `place_batch`); ``'stack'`` is an
        already-np.stack'ed (K, B, ...) fused-dispatch payload
        (→ `place_stacked_batch`)."""
        if kind == "stack":
            return self.place_stacked_batch(payload)
        return self.place_batch(payload)

    def place_stacked_batch(
        self, stacked: Dict[str, np.ndarray]
    ) -> Dict[str, jax.Array]:
        """Place a (K, B, ...) stack of K per-step batches; the K axis is
        never sharded (it is scanned over), each step's batch keeps this
        strategy's per-batch sharding."""
        if self.mesh is None:
            dev = jax.devices()[0]
            return {k: jax.device_put(v, dev) for k, v in stacked.items()}
        sharding = self._stacked_sharding()
        return {k: jax.device_put(v, sharding) for k, v in stacked.items()}

    def _stacked_sharding(self) -> NamedSharding:
        """`batch_sharding` shifted right by the leading K axis."""
        return NamedSharding(
            self.mesh, P(None, *tuple(self.batch_sharding.spec))
        )

    # -- compiled steps -----------------------------------------------------
    def _train_loss_impl(self) -> Optional[Callable]:
        """The fused Pallas training loss when the kernel policy engages
        it (``--kernels pallas`` or the legacy ``--pallas`` alias; None =
        XLA loss). Single-device runs use the kernel directly; mesh
        strategies wrap it in shard_map — per-shard kernel + a 4-scalar
        stats psum over the batch-sharding axes — so the loss and its
        custom-VJP gradient equal the unsharded computation
        (ops/fused_loss.py)."""
        if not self.kernels.train_loss_fused:
            return None
        from distributedpytorch_tpu.ops.fused_loss import (
            fused_bce_dice_loss,
            make_sharded_fused_loss,
            spec_axes,
        )

        if self.mesh is None:
            return fused_bce_dice_loss
        spec = self.batch_sharding.spec
        return make_sharded_fused_loss(self.mesh, spec, spec_axes(spec))

    def _raw_step(self, model, tx) -> Callable:
        """The unjitted per-batch step this mesh point runs: the
        explicit pipeline schedule when a 'stage' axis exists, the plain
        (GSPMD-sharded) step otherwise — ONE definition for every
        strategy."""
        if self.is_pipeline:
            return self._pipeline_raw_step(model, tx)
        # Quirk-1 scale uses the PER-PROCESS batch_size (the reference's
        # `-b` value): fit_DDP scales by its local -b then
        # mean-allreduces, so the global batch would overscale by world.
        return make_train_step(
            model,
            tx,
            batch_size=self.config.batch_size,
            faithful_loss_scaling=self.config.faithful_loss_scaling,
            remat=self.config.remat,
            loss_impl=self._train_loss_impl(),
            policy=self.policy,
        )

    def _pipeline_raw_step(self, model, tx) -> Callable:
        """The pipelined step over the 'stage' axis (either schedule);
        the data-axis plumbing — batch sharding, stats/grad psums over
        ('stage'[, 'data']) — derives from the mesh, one definition for
        MP, DDP_MP, and every stage-bearing mesh config."""
        pipeline_vag = make_pipeline_value_and_grad_fn(
            model,
            self.mesh,
            num_microbatches=self.config.num_microbatches,
            remat=self.config.remat,
            cuts=self.config.pipeline_cuts,
            use_pallas=self.kernels.train_loss_fused,
            schedule=self.config.pipeline_schedule,
            mesh_config=self.mesh_config,
        )
        # per-process batch, same rationale as the plain step's scale
        grad_scale = (
            float(self.config.batch_size)
            if self.config.faithful_loss_scaling
            else 1.0
        )

        def step(state: TrainState, batch):
            prepped = {"image": batch["image"], "mask": _prep_mask(batch["mask"])}
            loss, grads, model_state = pipeline_vag(
                state.params, state.model_state, prepped
            )
            # the wgrad contract at the schedule boundary: 1f1b already
            # accumulated in WGRAD_DTYPE; gpipe's autodiff emits grads in
            # the param dtype, so under bf16_params they are stated f32
            # here, before the faithful-quirk scale can round in bf16
            grads = self.policy.cast_grads(grads)
            if grad_scale != 1.0:
                grads = jax.tree.map(lambda g: g * grad_scale, grads)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return (
                TrainState(
                    params=params,
                    opt_state=opt_state,
                    step=state.step + 1,
                    model_state=model_state,
                ),
                loss,
            )

        return step

    def build_train_step(self, model, tx) -> Callable:
        return jax.jit(self._raw_step(model, tx), donate_argnums=_state_donation(self.config))

    def build_multi_train_step(self, model, tx) -> Callable:
        """K steps per dispatch: `multi(state, stacked) -> (state, losses)`
        with batches stacked on a leading axis (see make_multi_train_step;
        place the stacked batch with `place_stacked_batch`)."""
        multi = make_multi_train_step(self._raw_step(model, tx))
        return jax.jit(multi, donate_argnums=_state_donation(self.config))

    def build_accum_train_step(self, model, tx) -> Callable:
        """ONE optimizer step over config.grad_accum stacked batches with
        one chunk's activation memory — exact for the non-additive
        log-dice loss (see make_accum_train_step). The fused Pallas stats
        run only off-mesh: inside this plain GSPMD jit a sharded chunk
        cannot enter pallas_call (unlike the per-shard shard_map loss)."""
        if self.is_pipeline:
            raise ValueError(
                "pipeline strategies already microbatch inside the "
                "schedule — raise --microbatches instead of --grad-accum"
            )
        step = make_accum_train_step(
            model,
            tx,
            batch_size=self.config.batch_size,
            chunks=self.config.grad_accum,
            faithful_loss_scaling=self.config.faithful_loss_scaling,
            remat=self.config.remat,
            use_pallas=self.kernels.train_loss_fused and self.mesh is None,
        )
        return jax.jit(step, donate_argnums=_state_donation(self.config))

    def _forward_fn(self, model) -> Callable:
        return make_pipeline_forward_fn(
            model,
            self.mesh,
            num_microbatches=self.config.num_microbatches,
            cuts=self.config.pipeline_cuts,
            mesh_config=self.mesh_config,
        )

    def build_eval_step(self, model) -> Callable:
        if self.is_pipeline:
            # Eval runs the pipelined forward too (the reference
            # evaluates through the pipe model, train.py:62-64 →
            # evaluate.py). For stateful models `variables` is the
            # {'params','batch_stats'} dict the trainer's
            # _eval_variables() builds (running averages only).
            self._pallas_eval()  # warn if --pallas was requested: mesh strategy
            fwd = self._forward_fn(model)
            from distributedpytorch_tpu.ops.losses import (
                bce_dice_loss,
                dice_coefficient,
            )

            def eval_step(variables, batch):
                preds = fwd(variables, batch["image"])
                target = _prep_mask(batch["mask"])
                return {
                    "loss": bce_dice_loss(preds, target),
                    "dice": dice_coefficient(preds, target),
                }

            return jax.jit(eval_step)
        return jax.jit(make_eval_step(model, use_pallas=self._pallas_eval()))

    # -- sharded evaluation -------------------------------------------------
    def eval_shard(self) -> ShardSpec:
        """Round-robin assignment of whole VAL BATCHES to processes
        (rank p evaluates global batches p, p+world, ...). Default: one
        shard — every process evaluates everything (single-process
        strategies have no one to share with)."""
        return ShardSpec(0, 1)

    def build_grouped_eval_step(self, model) -> Callable:
        """Eval step over a (world·b) stack of `world` independent val
        batches, one per process, sharded over the mesh exactly like a
        train batch; returns per-batch vector metrics (see
        train/steps.grouped_eval_metrics). Every process reads back
        identical values, so the plateau scheduler stays in lockstep while
        each process loads and computes only 1/world of the val set.

        Output shardings are pinned REPLICATED: left to itself GSPMD may
        shard the (world,) metric vectors over 'data' (one element per
        shard — exactly the layout), which multi-process hosts cannot
        device_get (elements live on non-addressable devices)."""
        groups = self.eval_shard().world
        if self.is_pipeline and self.mesh_config.per_process_batch:
            fwd = self._forward_fn(model)

            def eval_step(variables, batch):
                preds = fwd(variables, batch["image"])
                return grouped_eval_metrics(
                    preds, _prep_mask(batch["mask"]), groups
                )

            replicated = NamedSharding(self.mesh, P())
            return jax.jit(
                eval_step, out_shardings={"loss": replicated, "dice": replicated}
            )
        step = make_eval_step(model, groups=groups)
        if self.mesh is not None:
            replicated = NamedSharding(self.mesh, P())
            return jax.jit(
                step, out_shardings={"loss": replicated, "dice": replicated}
            )
        return jax.jit(step)

    def _pallas_eval(self) -> bool:
        """The fused EVAL stats kernel applies only where the eval batch
        is unsharded (single device / replicated): pallas_call has no
        GSPMD partitioning rule, so a mesh-sharded (B,H,W,1) input would
        fail to lower or force a de-shard. Sharded strategies keep the
        XLA eval metrics — the TRAINING loss still runs the fused kernel
        via the shard_map wrapper (`_train_loss_impl`), so only the
        per-epoch eval pass differs."""
        if not self.kernels.eval_stats_fused:
            return False
        if self.mesh is not None:
            import logging

            logging.getLogger(__name__).info(
                "--kernels: strategy %s trains through the fused kernel "
                "(shard_map); eval metrics stay on the XLA path (sharded "
                "eval batches cannot enter pallas_call)",
                self.name,
            )
            return False
        return True


class SingleDevice(Strategy):
    """Reference ``-t singleGPU`` (train.py:46-50): whole model + batch on
    one chip — the ``1x1x1`` mesh point."""

    name = "singleGPU"


def _coerce_leaf(x):
    """Python scalars → numpy before placement: a restored checkpoint's
    ``step`` counter is a plain int, which multi-process placement
    rejects outright."""
    return x if isinstance(x, (jax.Array, np.ndarray)) else np.asarray(x)


def _place_global(x, sharding: NamedSharding):
    """Place one leaf under a sharding that may span processes.

    On a multi-process mesh, every locally-materializable value — host
    numpy (the checkpoint-restore path) AND fully-addressable jax arrays
    (fresh single-device init) — goes through
    ``make_array_from_callback``: each process builds its own
    addressable shards from its (identical by construction: same seed,
    same checkpoint file) local copy, with NO cross-process transfer and
    NO collective. ``device_put`` onto a non-addressable sharding
    instead runs a gloo `assert_equal` allgather per leaf — a collective
    per parameter at every trainer construction, observed crashing gloo
    (`op.preamble.length <= op.nbytes`) when those host collectives
    interleave with XLA's own CPU collectives. Single-process keeps
    plain device_put.
    """
    x = _coerce_leaf(x)
    if jax.process_count() > 1:
        if isinstance(x, jax.Array):
            if not x.is_fully_addressable:
                return jax.device_put(x, sharding)  # already global
            x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx, v=x: v[idx]
        )
    return jax.device_put(x, sharding)


def _replicate(mesh: Mesh, tree):
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: _place_global(x, sharding), tree)


class DataParallel(Strategy):
    """Reference ``-t DP`` (torch.nn.DataParallel, train_utils.py:98):
    single process, batch split across local devices — the ``Nx1x1``
    point with replicated params and the torch-DP GLOBAL-batch
    convention. XLA's sharding propagation inserts the gradient
    AllReduce that DataParallel does with scatter/gather — without the
    per-step replica broadcast DataParallel pays."""

    name = "DP"

    def _mesh_layout(self, config, devices):
        devs = list(devices if devices is not None else jax.local_devices())
        n = _shrunk_data_degree(self.name, config.batch_size, len(devs))
        return MeshConfig(data=n, drop_last=True), devs[:n]


class MultiProcessMixin:
    """The torchrun-style multi-process contract, shared by every strategy
    with a 'data' mesh axis spanning processes (DDP, DDP_MP, DDP_SP,
    FSDP, mesh specs):

      * each process loads its own sample shard (`ShardSpec` = the
        DistributedSampler, reference train_utils.py:189, with the
        per-epoch reshuffle fix);
      * config.batch_size is PER-PROCESS (global = b × world), matching
        the torchrun launch convention (reference README.md:37);
      * lr is scaled by the data-parallel degree when the mesh point is
        lr-scaling-eligible (the DDP family) and
        ``ddp_lr_world_size_scaling`` is set (reference quirk 2,
        train_utils.py:199);
      * batches assemble from process-local data into one global array.

    Requires `self.mesh` with a 'data' axis and `self.batch_sharding`.

    Batch-dim sharding is by DATA ROW, not blindly by process. When the
    mesh has axes besides 'data' (stage in DDP_MP, spatial in DDP_SP),
    the devices of one data row can belong to SEVERAL processes — and
    `make_array_from_process_local_data` takes each process's local data
    as its own devices' shard content WITHOUT reconciling replicas, so
    co-row processes feeding different samples silently build a
    corrupted global batch (empirically: the same jitted `sum` of such a
    batch returns DIFFERENT values on different processes — each sees
    its own column's data; found by a 4-process × {data:2, stage:2}
    probe in round 5). Processes sharing a data row must therefore load
    the SAME samples; `_batch_replica_shard()` computes that row-based
    assignment from the global mesh (identical on every process), and
    both the train loader shard and the eval round-robin use it.
    """

    def _batch_replica_shard(self) -> ShardSpec:
        """(rank, world) for batch-dim loading: one shard per data ROW.

        Fast path: when every data row's devices belong to one process
        (1-axis DDP mesh; 2-proc × 2-device hybrids), this is the plain
        process round-robin — maximal parallelism, no redundant loading.
        When rows span processes, co-row processes get the SAME rank
        (they must feed identical data — see class docstring). If the
        topology is irregular (a process spanning rows that are also
        shared, or processes orphaned by a shrunk mesh), fall back to
        world=1 — every branch decides from the GLOBAL process→row map,
        so all processes pick the same regime (divergence here would
        mean different collective programs and a deadlock).

        Memoized: the mesh and process layout are fixed for the
        strategy's lifetime, and this sits on place_batch's per-step
        host path — an O(devices) Python scan per batch key would be
        real overhead on a pod."""
        cached = getattr(self, "_replica_shard_memo", None)
        if cached is not None:
            return cached
        spec = self._compute_batch_replica_shard()
        self._replica_shard_memo = spec
        return spec

    def _compute_batch_replica_shard(self) -> ShardSpec:
        if jax.process_count() == 1:
            return ShardSpec(0, 1)
        if self.mesh is None or "data" not in self.mesh.axis_names:
            return ShardSpec(0, 1)  # no data axis: every process loads all
        axis = self.mesh.axis_names.index("data")
        grid = np.moveaxis(self.mesh.devices, axis, 0)
        grid = grid.reshape(grid.shape[0], -1)
        row_procs = [{d.process_index for d in row} for row in grid]
        proc_rows = {}
        for i, procs in enumerate(row_procs):
            for p in procs:
                proc_rows.setdefault(p, set()).add(i)
        if set(proc_rows) != set(range(jax.process_count())):
            return ShardSpec(0, 1)  # orphaned processes: replicate
        if all(len(s) == 1 for s in row_procs):
            return ShardSpec(jax.process_index(), jax.process_count())
        if any(len(rows) != 1 for rows in proc_rows.values()):
            return ShardSpec(0, 1)
        my_row = next(iter(proc_rows[jax.process_index()]))
        return ShardSpec(my_row, len(row_procs))

    def data_shard(self) -> ShardSpec:
        return self._batch_replica_shard()

    def eval_shard(self) -> ShardSpec:
        """Multi-process strategies split evaluation: each process owns
        every world-th val batch and the grouped eval step psums nothing —
        per-batch metrics come back replicated from one sharded dispatch.
        Same row-based assignment as training (class docstring)."""
        return self._batch_replica_shard()

    @property
    def global_batch_size(self) -> int:
        # b × the number of DISTINCT batch shards (= data rows when rows
        # span processes) — not × process_count: co-row processes feed
        # the same samples, which add capacity only once.
        return self.config.batch_size * self.data_shard().world

    def lr_for(self, base_lr: float) -> float:
        if (
            self.config.ddp_lr_world_size_scaling
            and self.mesh_config.lr_scaling
        ):
            return base_lr * self.mesh.shape["data"]
        return base_lr

    def _global_shape(self, local_shape) -> tuple:
        """Global batch shape: dim 0 scales to the global batch; other
        dims are supplied at FULL extent by every process and
        `make_array_from_process_local_data` slices each device's part
        (how the spatial axis of DDP_SP distributes without the loader
        knowing about H-sharding — verified by the round-5 probe)."""
        return (self.global_batch_size,) + tuple(local_shape[1:])

    def place_batch(self, batch):
        if jax.process_count() == 1:
            return super().place_batch(batch)
        return {
            k: jax.make_array_from_process_local_data(
                self.batch_sharding, v, global_shape=self._global_shape(v.shape)
            )
            for k, v in batch.items()
        }

    def place_stacked_batch(self, stacked):
        if jax.process_count() == 1:
            return super().place_stacked_batch(stacked)
        sharding = self._stacked_sharding()
        return {
            k: jax.make_array_from_process_local_data(
                sharding,
                v,
                global_shape=(v.shape[0],)
                + self._global_shape(v.shape[1:]),
            )
            for k, v in stacked.items()
        }


class DistributedDataParallel(MultiProcessMixin, Strategy):
    """Reference ``-t DDP`` (train_utils.py:170-248): multi-process data
    parallel — the ``Nx1x1`` point over ALL processes' devices with the
    MultiProcessMixin contract (sample sharding, per-process batch, lr
    scaling); eval/checkpoint/metrics on process 0 only.

    Launch: `dist/runtime.py` maps torchrun-style env vars onto
    `jax.distributed.initialize`. Under a single process this degrades to
    DP over all local devices — which is also how it is unit-tested on
    the 8-device virtual CPU mesh.
    """

    name = "DDP"

    def _mesh_layout(self, config, devices):
        devs = list(devices if devices is not None else jax.devices())
        cfg = MeshConfig(
            data=len(devs), per_process_batch=True, lr_scaling=True,
            drop_last=True,
        )
        return cfg, devs


class Pipeline(Strategy):
    """Reference ``-t MP`` (unet_model.py:14-53): the ``1x1xS`` point —
    an S-stage microbatched pipeline, explicit schedule over a
    ('stage',) mesh (see parallel/pipeline.py). ``--pipeline-schedule``
    picks ``gpipe`` (fill-drain) or ``1f1b`` (PipeDream-flush; in-flight
    activations bounded by the stage count). Stateful (BatchNorm) models
    thread their batch_stats through the stages under either schedule."""

    name = "MP"

    def _mesh_layout(self, config, devices):
        _validate_pipeline_schedule(config)
        devs = list(devices if devices is not None else jax.local_devices())
        if len(devs) < config.num_stages:
            raise ValueError(
                f"Requires at least {config.num_stages} devices, got {len(devs)}"
            )
        return MeshConfig(stage=config.num_stages), devs


class HybridDataPipeline(MultiProcessMixin, Strategy):
    """``-t DDP_MP``: data parallel × pipeline — the ``Dx1xS`` point.
    Batch sharded over 'data'; each data replica runs the S-stage
    schedule (either --pipeline-schedule) over its 'stage' group; the
    gradient psum over 'data' is the DDP all-reduce — inserted by
    autodiff under gpipe, issued explicitly by the 1F1B schedule's final
    grad reduction."""

    name = "DDP_MP"

    def _mesh_layout(self, config, devices):
        _validate_pipeline_schedule(config)
        devs = list(devices if devices is not None else jax.devices())
        stages = config.num_stages
        if len(devs) < 2 * stages:
            raise ValueError(
                f"DDP_MP needs at least {2*stages} devices, got {len(devs)}"
            )
        # Each data shard must hold ≥1 full microbatch set: shrink the data
        # degree until batch divides dp × microbatches (mirrors DP's
        # mesh shrink for indivisible batches).
        per_process = config.batch_size
        mb = config.num_microbatches
        if per_process % mb:
            raise ValueError(
                f"batch_size {per_process} must be a multiple of "
                f"num_microbatches {mb}"
            )
        dp = min(len(devs) // stages, per_process // mb)
        while per_process % (dp * mb):
            dp -= 1
        if dp < 2:
            raise ValueError(
                f"DDP_MP degenerates to plain MP: batch_size {per_process} with "
                f"{mb} microbatches leaves no room for a data axis ≥ 2 — "
                f"use -t MP or raise the batch size"
            )
        cfg = MeshConfig(
            data=dp, stage=stages, per_process_batch=True, lr_scaling=True,
            drop_last=True,
        )
        return cfg, devs


class SpatialParallel(Strategy):
    """``-t SP``: spatial (image-plane) sharding — the ``1xMx1@sp``
    point, the conv-net analogue of sequence/context parallelism.

    The image H axis is sharded over the model axis (named 'spatial');
    params stay replicated. Under GSPMD, XLA inserts the halo exchanges
    (collective-permute of boundary rows) that each 3×3 conv window and
    2×2 pool needs at shard edges. Activation memory per chip drops by
    the mesh size, so batch-1 images far beyond one chip's HBM train
    without pipeline bubbles.

    Constraint: H must stay divisible by the mesh size after the pools
    (H/2^L rows at the deepest level), or GSPMD pads ragged shards; the
    constructor shrinks the mesh until it divides evenly.
    """

    name = "SP"

    def _mesh_layout(self, config, devices):
        devs = list(devices if devices is not None else jax.local_devices())
        h = config.image_size[1]  # image_size is (W, H), reference newsize
        deep = 2 ** config.model_levels  # downsampling at the deepest level
        n = len(devs)
        while n > 1 and (h // deep) % n:
            n -= 1
        return MeshConfig(model=n, model_role="spatial"), devs[:n]


class HybridDataSpatial(MultiProcessMixin, Strategy):
    """``-t DDP_SP``: data × spatial — the ``DxMx1@sp`` point: batch
    over 'data', image rows over 'spatial', gradients all-reduced over
    both axes by GSPMD. Scale batch throughput and per-image footprint
    at once (multi-host: 'data' maps across hosts/DCN, 'spatial' stays
    inside the ICI domain where the per-conv halo exchanges are cheap)."""

    name = "DDP_SP"

    def _mesh_layout(self, config, devices):
        devs = list(devices if devices is not None else jax.devices())
        h = config.image_size[1]
        deep = 2 ** config.model_levels
        # Largest spatial degree that (a) divides the deepest level's rows
        # and (b) still leaves a data axis ≥ 2 that divides the batch.
        best = None
        for sp in range(len(devs), 0, -1):
            if (h // deep) % sp:
                continue
            dp = len(devs) // sp
            while dp > 1 and config.batch_size % dp:
                dp -= 1
            if dp >= 2:
                best = (dp, sp)
                break
        if best is None:
            raise ValueError(
                f"DDP_SP degenerates to plain SP: batch_size "
                f"{config.batch_size} leaves no data axis ≥ 2 over "
                f"{len(devs)} devices — use -t SP or raise the batch size"
            )
        dp, sp = best
        cfg = MeshConfig(
            data=dp, model=sp, model_role="spatial",
            per_process_batch=True, lr_scaling=True, drop_last=True,
        )
        return cfg, devs


def _shard_state_by_rule(state, mesh: Mesh, leaf_spec, strategy_name: str) -> Any:
    """Place a TrainState with per-leaf PartitionSpecs chosen by
    `leaf_spec(shape) -> PartitionSpec`. Adam's m/v mirror the param
    shapes, so one shape-driven rule shards params and optimizer state
    consistently; scalars (step/count) replicate.

    Warns loudly when NO leaf shards: the strategy then degenerates to
    fully replicated compute (every device does the whole model) — legal,
    but certainly not what the user asked for.
    """
    sharded = 0

    def place(x):
        nonlocal sharded
        x = _coerce_leaf(x)
        spec = leaf_spec(getattr(x, "shape", ()))
        if any(s is not None for s in spec):
            sharded += 1
        return _place_global(x, NamedSharding(mesh, spec))

    placed = jax.tree.map(place, state)
    if sharded == 0:
        import logging

        logging.getLogger(__name__).warning(
            "%s: no parameter axis divides the %d-device mesh — state is "
            "fully replicated and every device computes the whole model "
            "(no parallel speedup or memory saving). Use a device count "
            "that divides the channel widths.",
            strategy_name,
            mesh.devices.size,
        )
    return placed


class TensorParallel(Strategy):
    """``-t TP``: tensor (model) parallelism — the ``1xMx1`` point with
    the ``channel`` params rule: conv out-channels sharded over
    ('model',).

    TPU-native form: pure sharding annotation. Every conv kernel
    (Kh, Kw, Cin, Cout) and bias is sharded on its out-channel axis; the
    batch is replicated. Under GSPMD each device then computes its channel
    slice of every layer, and XLA inserts the collectives where channels
    must be whole (the next layer contracts over the sharded Cin; skip
    concats; the 1-channel segmap head stays replicated — its Cout=1 does
    not divide). Parameters AND Adam state are sharded, so per-chip
    parameter memory drops by the mesh size — the memory effect of
    Megatron-style TP without hand-written collectives.

    Channel plan divisibility: widths 32..512 divide any power-of-two mesh
    up to 8; kernels whose out-axis does not divide (segmap, tiny test
    widths) replicate, which GSPMD handles per-tensor.
    """

    name = "TP"

    def _mesh_layout(self, config, devices):
        devs = list(devices if devices is not None else jax.local_devices())
        return MeshConfig(model=len(devs), params="channel"), devs


class FullyShardedDataParallel(MultiProcessMixin, Strategy):
    """``-t FSDP``: ZeRO-3-style fully sharded data parallel — the
    ``Nx1x1@fsdp`` point: batch sharded over ('data',) exactly like DP,
    but parameters and Adam state are ALSO sharded over 'data' (each
    leaf along its largest divisible axis). GSPMD inserts the per-layer
    all-gather of params in the forward/backward and the reduce-scatter
    of gradients — the ZeRO dance — from annotations alone.

    Multi-process capable (ZeRO semantics, unlike torch-DP-shaped ``DP``):
    the mesh spans EVERY process's devices and the MultiProcessMixin
    contract applies — per-process batch (global = b × data rows), sample
    sharding, process-local batch assembly. Sharded state on a pod is not
    fully addressable on any one host; checkpointing allgathers each such
    leaf collectively (checkpoint._to_host). The DDP lr × world quirk is
    NOT applied: FSDP is a memory layout, not the reference's DDP recipe.
    """

    name = "FSDP"

    def _mesh_layout(self, config, devices):
        if devices is not None or jax.process_count() == 1:
            # single-process (or explicit devices): exactly DP's mesh,
            # including the shrink-to-largest-divisor warning path
            devs = list(devices if devices is not None else jax.local_devices())
            n = _shrunk_data_degree(self.name, config.batch_size, len(devs))
            cfg = MeshConfig(
                data=n, params="fsdp", per_process_batch=True, drop_last=True,
            )
            return cfg, devs[:n]
        devs = list(jax.devices())
        if (config.batch_size * jax.process_count()) % len(devs) != 0:
            raise ValueError(
                f"FSDP: global batch {config.batch_size} × "
                f"{jax.process_count()} processes must divide the "
                f"{len(devs)}-device mesh"
            )
        cfg = MeshConfig(
            data=len(devs), params="fsdp", per_process_batch=True,
            drop_last=True,
        )
        return cfg, devs


class GenericMesh(MultiProcessMixin, Strategy):
    """``-t DxMxS[@rule[+rule]]``: an arbitrary point in mesh-shape
    space (parallel/mesh.py grammar) — including the hybrids no legacy
    class expresses: ``2x2x1`` (DP x TP), ``2x2x1@fsdp`` (FSDP x TP),
    ``2x4x1@sp`` (DDP_SP's geometry), ``4x1x2`` (DDP_MP's).

    Semantics follow the multi-process (torchrun/FSDP) convention:
    ``batch_size`` is per-process, no DDP lr quirk. Explicit specs fail
    LOUDLY on infeasible divisibility (no silent mesh shrinking — the
    user named an exact geometry). ``stage > 1`` with ``model > 1``
    runs the pipeline schedules with IN-STAGE sharding: the mesh's
    per-tree params rule (channel-TP over 'model', ZeRO over 'data')
    applies inside the stage functions (parallel/pipeline.py, module
    docstring "In-stage sharding"). The one remaining refusal is the
    'spatial' model role inside a stage — its halo exchanges cannot
    ride the tick program's stage-gated conds."""

    name = "mesh"

    def _mesh_layout(self, config, devices):
        cfg = mesh_rules.parse_mesh_spec(config.train_method)
        self.name = mesh_rules.canonical_spec(cfg)
        devs = list(devices if devices is not None else jax.devices())
        if cfg.size > len(devs):
            raise ValueError(
                f"mesh {self.name} needs {cfg.size} devices, "
                f"got {len(devs)}"
            )
        if cfg.stage > 1 and cfg.model > 1 and cfg.model_role == "spatial":
            raise ValueError(
                f"mesh {self.name}: a 'spatial' model role inside a "
                f"pipeline stage is not executable — spatial sharding "
                f"halo-exchanges inside every schedule tick, which the "
                f"stage-gated lax.cond program cannot carry; use the "
                f"channel role on the model axis "
                f"('{cfg.data}x{cfg.model}x{cfg.stage}') or keep spatial "
                f"sharding on a flat mesh "
                f"('{cfg.data}x{cfg.model}x1@sp')"
            )
        # divisibility is judged on the GLOBAL batch: mesh specs use
        # the torchrun convention (batch_size is per-process) while the
        # data axis spans ALL processes — `-t 8x1x1 -b 4` on 2 hosts is
        # global batch 8 over data=8, a launch DDP accepts (FSDP's
        # multi-process check in this file uses the same product)
        global_batch = config.batch_size * jax.process_count()
        if cfg.stage > 1:
            _validate_pipeline_schedule(config)
            mb = config.num_microbatches
            if global_batch % (cfg.data * mb):
                raise ValueError(
                    f"mesh {self.name}: global batch {global_batch} "
                    f"(batch_size {config.batch_size} x "
                    f"{jax.process_count()} processes) must be a "
                    f"multiple of data x microbatches = {cfg.data} x {mb}"
                )
        elif cfg.data > 1 and global_batch % cfg.data:
            raise ValueError(
                f"mesh {self.name}: global batch {global_batch} "
                f"(batch_size {config.batch_size} x "
                f"{jax.process_count()} processes) must divide the data "
                f"axis ({cfg.data}) — explicit mesh specs never shrink "
                f"silently"
            )
        if cfg.model > 1 and cfg.model_role == "spatial":
            h = config.image_size[1]
            deep = 2 ** config.model_levels
            if (h // deep) % cfg.model:
                raise ValueError(
                    f"mesh {self.name}: the deepest level's {h // deep} "
                    f"image rows must divide the spatial axis "
                    f"({cfg.model})"
                )
        return cfg, devs


STRATEGIES = {
    cls.name: cls
    for cls in (
        SingleDevice,
        DataParallel,
        DistributedDataParallel,
        Pipeline,
        HybridDataPipeline,
        SpatialParallel,
        HybridDataSpatial,
        TensorParallel,
        FullyShardedDataParallel,
    )
}


def build_strategy(config: TrainConfig, devices=None) -> Strategy:
    """Resolve ``config.train_method`` — a legacy strategy name (an
    alias into mesh-shape space) or a ``DxMxS[@rule]`` mesh spec — to a
    constructed strategy."""
    cls = STRATEGIES.get(config.train_method)
    if cls is not None:
        return cls(config, devices)
    if mesh_rules.is_mesh_spec(config.train_method):
        return GenericMesh(config, devices)
    raise ValueError(
        f"Unknown train method {config.train_method!r}; "
        f"expected one of {sorted(STRATEGIES)} or a mesh spec "
        f"DxMxS[@fsdp|sp] (docs/DISTRIBUTED.md 'The mesh engine')"
    )
