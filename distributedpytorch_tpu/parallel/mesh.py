"""The composable N-D mesh engine: one rule set, every strategy.

The six hand-written strategy classes (DP/SP/TP/FSDP/MP/DDP_MP plus the
hybrids) all reduce to points in ONE space: an N-D device mesh over the
axes ``('data', 'model', 'stage')`` plus per-tree sharding rules —

``data``
    batch-dimension parallelism. Batches shard their leading axis here;
    gradients reduce over it (the DDP all-reduce — autodiff-inserted for
    GSPMD configs, the explicit schedule-closing psum for pipelined
    ones). The ``fsdp`` params rule additionally shards parameters and
    optimizer state over this axis (ZeRO-3).
``model``
    model-dimension parallelism, in one of two roles: ``channel`` shards
    conv out-channels (Megatron-style TP — parameters and Adam state
    shard on their out-channel axis, XLA inserts the channel
    collectives) and ``spatial`` shards the image H axis (the conv-net
    analogue of sequence parallelism — XLA inserts the per-conv halo
    exchanges). Legacy meshes name this axis by its role (``'model'`` /
    ``'spatial'``) and the engine preserves that naming.
``stage``
    pipeline parallelism: the explicit shard_map schedules of
    parallel/pipeline.py (gpipe / 1f1b) over S stages.

A :class:`MeshConfig` is one point: axis sizes + the params rule + the
batch/LR semantics. Every legacy ``-t`` strategy is a **named alias**
into this space (:data:`LEGACY_PATTERNS`, concrete shapes resolved
against the device pool at build time), and arbitrary points launch as
``-t DxMxS[@rule[+rule]]`` mesh specs — e.g. ``-t 2x2x1`` (DP x TP,
inexpressible under the class-per-strategy design), ``-t 8x1x1@fsdp``
(FSDP), ``-t 2x4x1@sp`` (DDP_SP), ``-t 4x1x2`` (DDP_MP's geometry).

This module is **import-light (no jax at module level)**: the dptlint
contract derivation (analysis/collectives.py), the planner's jax-free
plan-file path, and the elastic supervisor all import it without paying
for a backend. Functions that construct jax objects import lazily.

Execution limits (honest, enforced at strategy construction):
``stage > 1`` composes with ``model > 1`` (channel role) and ``@fsdp``
— the pipeline schedules apply this module's per-tree rules IN-STAGE
(parallel/pipeline.py "In-stage sharding": params enter the shard_map
sharded per-leaf and are reconstructed with tiled all_gathers at the
top of the step). The one remaining refusal is the 'spatial' model
role inside a stage: its conv halo exchanges would have to run inside
every tick's stage-gated cond, which the schedule's ppermute program
cannot carry. The planner records THAT point as an infeasible
``config:`` reject instead of guessing.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

#: Canonical axis order. The built Mesh drops size-1 axes (a pure-DP
#: mesh is 1-D ``('data',)``, exactly the legacy layout), and the model
#: axis is named by its role.
AXES = ("data", "model", "stage")

#: params-rule vocabulary (how parameters AND Adam state shard):
#:   replicate    — full copy per device (DP/DDP/SP/MP and hybrids);
#:   channel      — out-channel axis over 'model' (TP);
#:   fsdp         — each leaf's largest divisible axis over 'data' (ZeRO-3);
#:   fsdp+channel — both at once (out-channel over 'model', largest
#:                  remaining axis over 'data').
PARAMS_RULES = ("replicate", "channel", "fsdp", "fsdp+channel")

MODEL_ROLES = ("channel", "spatial")

_SPEC_RE = re.compile(r"^(\d+)x(\d+)x(\d+)(?:@([a-z0-9+]+))?$")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """One point in mesh-shape space: axis sizes, sharding rules, and
    the batch/LR semantics the strategy layer reads."""

    data: int = 1
    model: int = 1
    stage: int = 1
    #: what the 'model' axis parallelizes — "channel" (TP) | "spatial" (SP)
    model_role: str = "channel"
    #: how params/opt-state shard — one of PARAMS_RULES
    params: str = "replicate"
    #: torchrun convention (batch_size is PER-PROCESS, global = b x data
    #: rows) vs torch-DP convention (batch_size is the global batch)
    per_process_batch: bool = False
    #: eligible for the reference's lr x world quirk (DDP family only)
    lr_scaling: bool = False
    #: sharded-batch strategies need the batch divisible by 'data'
    drop_last: bool = False

    def __post_init__(self):
        for axis in AXES:
            if int(getattr(self, axis)) < 1:
                raise ValueError(f"mesh axis {axis!r} must be >= 1")
        if self.params not in PARAMS_RULES:
            raise ValueError(
                f"params rule must be one of {PARAMS_RULES}, "
                f"got {self.params!r}"
            )
        if self.model_role not in MODEL_ROLES:
            raise ValueError(
                f"model_role must be one of {MODEL_ROLES}, "
                f"got {self.model_role!r}"
            )

    @property
    def size(self) -> int:
        return int(self.data) * int(self.model) * int(self.stage)

    @property
    def model_axis_name(self) -> str:
        """The model axis carries its ROLE as its mesh name — 'spatial'
        halo exchanges and 'model' channel collectives read differently
        in every trace, and the legacy meshes already named them so."""
        return "spatial" if self.model_role == "spatial" else "model"

    @property
    def is_pipeline(self) -> bool:
        return self.stage > 1


def axis_layout(cfg: MeshConfig) -> Tuple[Tuple[str, int], ...]:
    """((axis name, size), ...) for the axes with size > 1, in canonical
    (data, model, stage) order — the built Mesh's exact layout. Empty
    for the 1x1x1 point (no mesh: single device)."""
    layout: List[Tuple[str, int]] = []
    if cfg.data > 1:
        layout.append(("data", int(cfg.data)))
    if cfg.model > 1:
        layout.append((cfg.model_axis_name, int(cfg.model)))
    if cfg.stage > 1:
        layout.append(("stage", int(cfg.stage)))
    return tuple(layout)


def build_mesh(cfg: MeshConfig, devices: Sequence):
    """The jax Mesh for this config over ``devices`` (first size many),
    or None for the single-device point. Size-1 axes are dropped, so
    every legacy strategy's mesh reproduces its historical layout
    bit-for-bit (same devices, same axis names, same order)."""
    layout = axis_layout(cfg)
    if not layout:
        return None
    import numpy as np
    from jax.sharding import Mesh

    names = tuple(n for n, _ in layout)
    sizes = tuple(s for _, s in layout)
    total = 1
    for s in sizes:
        total *= s
    if len(devices) < total:
        raise ValueError(
            f"mesh {canonical_spec(cfg)} needs {total} devices, "
            f"got {len(devices)}"
        )
    return Mesh(np.array(list(devices[:total])).reshape(sizes), names)


def batch_partition_spec(cfg: MeshConfig):
    """The batch tree's PartitionSpec under this config: leading axis
    over 'data', image H (axis 1) over a spatial model axis, replicated
    otherwise — the one batch rule every strategy used to hand-write."""
    from jax.sharding import PartitionSpec as P

    if cfg.model > 1 and cfg.model_role == "spatial":
        return P("data" if cfg.data > 1 else None, cfg.model_axis_name)
    if cfg.data > 1:
        return P("data")
    return P()


def state_leaf_spec(cfg: MeshConfig, shape):
    """Per-leaf PartitionSpec for params/opt-state under the config's
    params rule. Adam's m/v mirror the param shapes, so one shape-driven
    rule shards both consistently; scalars and indivisible leaves
    replicate (GSPMD handles per-tensor fallback).

    ``channel``: the out-channel (last) axis over 'model' when it
    divides. ``fsdp``: the largest axis that divides 'data'.
    ``fsdp+channel``: channel first, then the largest REMAINING axis
    over 'data' — composable by construction."""
    from jax.sharding import PartitionSpec as P

    ndim = len(shape)
    if ndim == 0:
        return P()
    spec: List[Optional[str]] = [None] * ndim
    rule = cfg.params
    if rule in ("channel", "fsdp+channel") and cfg.model > 1:
        size = int(cfg.model)
        if shape[-1] % size == 0 and shape[-1] >= size:
            spec[-1] = cfg.model_axis_name
    if rule in ("fsdp", "fsdp+channel") and cfg.data > 1:
        size = int(cfg.data)
        axes = sorted(range(ndim), key=lambda i: -shape[i])
        for i in axes:
            if spec[i] is None and shape[i] % size == 0 and shape[i] >= size:
                spec[i] = "data"
                break
    return P(*spec)


# -- mesh-spec grammar -------------------------------------------------------
def is_mesh_spec(name) -> bool:
    """Does this ``-t`` value look like a mesh spec (``DxMxS[@opts]``)?
    Syntactic only — ``parse_mesh_spec`` validates semantics."""
    return isinstance(name, str) and _SPEC_RE.match(name) is not None


def parse_mesh_spec(spec: str) -> MeshConfig:
    """``DxMxS[@opt[+opt]]`` -> MeshConfig. Options: ``tp`` (channel
    model axis, the default), ``sp`` (spatial model axis), ``fsdp``
    (params/opt-state sharded over 'data'). Mesh-spec strategies use the
    multi-process (torchrun/FSDP) batch convention: ``batch_size`` is
    per-process, no DDP lr scaling."""
    m = _SPEC_RE.match(str(spec))
    if m is None:
        raise ValueError(
            f"not a mesh spec: {spec!r} (expected DxMxS[@opt[+opt]], "
            f"e.g. 4x1x2, 2x2x1@fsdp, 1x4x1@sp)"
        )
    data, model, stage = (int(m.group(i)) for i in (1, 2, 3))
    opts = set((m.group(4) or "").split("+")) - {""}
    unknown = opts - {"tp", "sp", "fsdp"}
    if unknown:
        raise ValueError(
            f"mesh spec {spec!r}: unknown option(s) {sorted(unknown)} "
            f"(known: tp, sp, fsdp)"
        )
    if "sp" in opts and "tp" in opts:
        raise ValueError(
            f"mesh spec {spec!r}: the model axis is either spatial (sp) "
            f"or channel (tp), not both"
        )
    if "sp" in opts and model <= 1:
        raise ValueError(
            f"mesh spec {spec!r}: @sp needs a model axis > 1 to shard "
            f"image rows over"
        )
    role = "spatial" if "sp" in opts else "channel"
    if "fsdp" in opts:
        params = "fsdp+channel" if (model > 1 and role == "channel") else "fsdp"
    elif model > 1 and role == "channel":
        params = "channel"
    else:
        params = "replicate"
    return MeshConfig(
        data=data, model=model, stage=stage, model_role=role, params=params,
        per_process_batch=True, lr_scaling=False, drop_last=data > 1,
    )


def canonical_spec(cfg: MeshConfig) -> str:
    """The round-trippable spec string for a config — what checkpoint
    manifests record as ``mesh_spec`` and what docs/tables print."""
    opts = []
    if cfg.model > 1 and cfg.model_role == "spatial":
        opts.append("sp")
    if "fsdp" in cfg.params:
        opts.append("fsdp")
    suffix = ("@" + "+".join(opts)) if opts else ""
    return f"{cfg.data}x{cfg.model}x{cfg.stage}{suffix}"


def spec_is_pipeline(name) -> bool:
    """Does this ``-t`` value name a mesh spec with a stage axis? Cheap
    and non-raising — jax-free callers (the elastic preflight, the
    planner's grid walk) gate schedule enumeration on it."""
    m = _SPEC_RE.match(str(name)) if isinstance(name, str) else None
    return m is not None and int(m.group(3)) > 1


def spec_is_hybrid(name) -> bool:
    """>= 2 non-trivial axes — what the bench sweep and the planner's
    leg mapping mean by a 'hybrid' geometry."""
    m = _SPEC_RE.match(str(name)) if isinstance(name, str) else None
    if m is None:
        return False
    return sum(int(m.group(i)) > 1 for i in (1, 2, 3)) >= 2


# -- legacy strategies as named points ---------------------------------------
#: Structural pattern of each legacy ``-t`` strategy (axis sizes are
#: placeholders — 2 means "spans devices", resolved concretely at
#: strategy construction; what matters here is WHICH axes exist and
#: which rules apply). Single source for the dptlint contract
#: derivation and the docs' strategy -> mesh-shape table.
LEGACY_PATTERNS: Dict[str, MeshConfig] = {
    "singleGPU": MeshConfig(),
    "DP": MeshConfig(data=2, drop_last=True),
    "DDP": MeshConfig(data=2, per_process_batch=True, lr_scaling=True,
                      drop_last=True),
    "SP": MeshConfig(model=2, model_role="spatial"),
    "DDP_SP": MeshConfig(data=2, model=2, model_role="spatial",
                         per_process_batch=True, lr_scaling=True,
                         drop_last=True),
    "TP": MeshConfig(model=2, params="channel"),
    "FSDP": MeshConfig(data=2, params="fsdp", per_process_batch=True,
                       drop_last=True),
    "MP": MeshConfig(stage=2),
    "DDP_MP": MeshConfig(data=2, stage=2, per_process_batch=True,
                         lr_scaling=True, drop_last=True),
}


# -- contract derivation (the dptlint tables) --------------------------------
def derive_jaxpr_contract(
    cfg: MeshConfig, schedule: Optional[str]
) -> Tuple[Tuple[str, frozenset, bool, str], ...]:
    """The trace-level comms contract a config's train step must
    satisfy, derived from the sharding rules instead of a hand-kept
    table: rows are ``(kind, axes, grad_output, why)`` —
    ``analysis/collectives.JaxprComm``'s field order.

    GSPMD-only configs (no stage axis) have EMPTY jaxpr programs (XLA
    inserts their collectives at compile time; the HLO tier owns them).
    Pipelined configs must show the inter-stage ppermutes and the
    whole-batch stats psum; the 1f1b schedule additionally must show the
    schedule-closing output-feeding gradient psum — whose 'data' axis IS
    the DDP all-reduce on data-hybrid meshes. In-stage-sharded hybrids
    (``model > 1`` channel role, or ``@fsdp`` with ``data > 1``) must
    additionally show the per-step param-reconstruction all_gathers the
    stage bodies run over the sharding axis (parallel/pipeline.py
    ``_gather_params``) — the static checker covers these points
    NON-EXEMPT, same as the flat schedules."""
    if not cfg.is_pipeline:
        return ()
    axes = frozenset({"stage"} | ({"data"} if cfg.data > 1 else set()))
    hybrid = cfg.data > 1
    rows: List[Tuple[str, frozenset, bool, str]] = [
        ("ppermute", frozenset({"stage"}), False,
         "inter-stage activation transfers"
         if schedule == "gpipe" else
         "inter-stage activation/cotangent transfers"),
        ("psum", axes, False,
         "whole-batch loss-stats reduction"
         + (" across stages AND data shards" if hybrid
            and schedule == "gpipe" else "")),
    ]
    if cfg.model > 1 and cfg.model_role == "channel":
        rows.append((
            "all_gather", frozenset({"model"}), False,
            "in-stage channel-TP param reconstruction (gather-at-use, "
            "once per step at the top of the shard_map body)",
        ))
    if "fsdp" in cfg.params and cfg.data > 1:
        rows.append((
            "all_gather", frozenset({"data"}), False,
            "in-stage ZeRO param reconstruction over the data axis "
            "(gather-at-use, once per step)",
        ))
    if schedule == "1f1b":
        rows.append((
            "psum", axes, True,
            "schedule-closing gradient psum — the 'data' axis IS the "
            "DDP all-reduce" if hybrid else
            "schedule-closing gradient assembly across stages",
        ))
    return tuple(rows)


def derive_eval_jaxpr_contract(
    cfg: MeshConfig, schedule: Optional[str]
) -> Tuple[Tuple[str, frozenset, bool, str], ...]:
    """The trace-level comms contract a config's EVAL step must
    satisfy — same row shape as :func:`derive_jaxpr_contract`, derived
    from the same sharding rules. The eval program is the train
    program's forward slice: the inter-stage activation ppermutes and
    the in-stage param-reconstruction all_gathers survive, the
    loss-stats reduction becomes an output-feeding psum over 'stage'
    ONLY (eval stats are reduced across stages but returned per data
    shard — the host averages shards, so no 'data' axis appears even on
    hybrids), and the 1f1b gradient row vanishes with the backward
    pass (eval runs the gpipe-shaped forward under either schedule).
    GSPMD configs stay empty here, same as train."""
    if not cfg.is_pipeline:
        return ()
    rows: List[Tuple[str, frozenset, bool, str]] = [
        ("ppermute", frozenset({"stage"}), False,
         "inter-stage activation transfers (eval forward)"),
        ("psum", frozenset({"stage"}), True,
         "output-feeding eval loss/accuracy-stats reduction across "
         "stages — dropping it ships stage-local metrics as if global"),
    ]
    if cfg.model > 1 and cfg.model_role == "channel":
        rows.append((
            "all_gather", frozenset({"model"}), False,
            "in-stage channel-TP param reconstruction (eval forward "
            "gathers at use, same as train)",
        ))
    if "fsdp" in cfg.params and cfg.data > 1:
        rows.append((
            "all_gather", frozenset({"data"}), False,
            "in-stage ZeRO param reconstruction over the data axis "
            "(eval forward gathers at use, same as train)",
        ))
    return tuple(rows)


def channel_comms_required(cfg: MeshConfig) -> bool:
    """Does this config carry a channel-sharded model axis? Its HLO
    must then show SOME channel collective — XLA picks the mechanism
    per version, so the requirement is the any-of tier
    (analysis/collectives.TP_HLO_ANY_OF), checked IN ADDITION to the
    exact set below: a DP x TP hybrid whose data-axis all-reduce
    regresses away must still fail, any-of satisfied or not."""
    return cfg.model > 1 and cfg.model_role == "channel"


def derive_hlo_contract(cfg: MeshConfig) -> frozenset:
    """Exactly-required optimized-HLO collectives for a config's
    compiled train step, derived from the rules. The channel model
    axis contributes through :func:`channel_comms_required` (the
    any-of tier) instead — its mechanism is XLA's choice — so a pure
    channel-TP config derives an empty exact set here."""
    required = set()
    if cfg.stage > 1:
        required.add("collective-permute")      # ppermute stage transfers
    if cfg.model > 1 and cfg.model_role == "spatial":
        required.add("collective-permute")      # conv halo exchanges
    if cfg.data > 1:
        if "fsdp" in cfg.params:
            required.add("all-gather")          # ZeRO param gathering
        else:
            required.add("all-reduce")          # gradient reduction
    return frozenset(required)
