"""The one trainer: epoch driver shared by every strategy.

Replaces the reference's three ~70-line copies (`fit`/`fit_DP`/`fit_DDP`,
reference utils/train_utils.py:22-248) with a single loop; everything that
differed between them lives in the Strategy object (parallel/strategy.py).

Loop semantics parity (reference train_utils.py:49-92):
  * per-step: forward/backward/Adam with the batch_size loss-scaling quirk
    (inside the jitted step), UNSCALED loss recorded;
  * every 10 steps: append (global_step, wall_time, mean of last ≤10 losses);
  * per-epoch: evaluate → val (Step, Time, Loss) row → plateau scheduler;
  * end: checkpoint + pandas pickles + logfile lines.

Deliberate fixes over the reference (each flagged in SURVEY.md §2):
  * periodic mid-run checkpoints with optimizer/scheduler/step state → real
    crash resume (the reference loses everything before the final epoch);
  * scheduler state is part of the checkpoint, and in multi-process runs the
    val loss driving it is computed identically everywhere (quirk 7's
    rank-divergent lr cannot happen: lr lives in replicated optimizer state);
  * per-epoch reshuffle of the sharded train set (missing set_epoch, §3.2).

Host/device split (SURVEY.md §7 hard-part 2): the epoch loop is a fully
overlapped pipeline. Decoded samples persist across epochs in a
memory-budgeted host cache (data/dataset.SampleCache); stacking and
host→device placement run on a prefetch worker `prefetch_batches` payloads
ahead of the step loop (utils/prefetch.pipelined_placement → the
strategy's `place_work`); the jitted step returns the loss as a device
scalar that LossRecords drains asynchronously at row/epoch boundaries;
and checkpoint serialization+writes run on a background writer thread
(checkpoint.save_checkpoint_async), drained before train() returns. Each
phase is observable through the step-timeline tracer (utils/trace.py,
``--trace-timeline``).

Resilience (docs/RELIABILITY.md): non-finite-loss policies riding the
metrics readback (``abort`` / ``rollback``-to-checkpoint / ``skip``),
bounded-backoff retries for transient decode/placement failures, a
dispatch watchdog that dumps the step timeline and checkpoints-and-stops,
and a deterministic fault-injection harness (utils/faults.py) proving
each path. Checkpoint saves build their payload on EVERY rank (the host
snapshot is a collective allgather when state is sharded across
processes) with only the file write rank-0-gated.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import signal
import time
from typing import Optional

import jax
import numpy as np

from distributedpytorch_tpu.checkpoint import (
    load_checkpoint,
    save_checkpoint,
    save_checkpoint_async,
)
from distributedpytorch_tpu.config import TrainConfig
from distributedpytorch_tpu.data import (
    DataLoader,
    SampleCache,
    build_dataset,
    seeded_split,
)
from distributedpytorch_tpu.evaluate import evaluate, evaluate_sharded
from distributedpytorch_tpu.obs import defs as obsm
from distributedpytorch_tpu.obs import flight
from distributedpytorch_tpu.ops.optim import get_learning_rate, set_learning_rate
from distributedpytorch_tpu.ops.schedule import ReduceLROnPlateau
from distributedpytorch_tpu.train.steps import create_train_state
from distributedpytorch_tpu.utils import faults
from distributedpytorch_tpu.utils.faults import NonFiniteLossError, StepWatchdog
from distributedpytorch_tpu.utils.metrics import LossRecords
from distributedpytorch_tpu.utils.prefetch import (
    pipelined_placement,
    stacked_work,
)
from distributedpytorch_tpu.utils.trace import StepTimeline

logger = logging.getLogger(__name__)


class Trainer:
    def __init__(
        self,
        config: TrainConfig,
        dataset=None,
        strategy=None,
        rng: Optional[jax.Array] = None,
    ):
        # local import: parallel/ imports train/steps, so importing it at
        # module scope would be circular
        from distributedpytorch_tpu.parallel import build_strategy

        self.config = config
        self.strategy = strategy or build_strategy(config)
        self.dataset = dataset if dataset is not None else self._build_dataset()
        self.rng = rng if rng is not None else jax.random.key(config.seed)
        # arm the fault-injection harness (inert when no specs). install()
        # is idempotent per spec list: fit_with_restarts rebuilds the
        # Trainer after a crash and already-fired counts must survive.
        faults.install(config.inject_faults)
        if config.nonfinite_policy not in ("abort", "rollback", "skip"):
            raise ValueError(
                f"nonfinite_policy must be abort|rollback|skip, got "
                f"{config.nonfinite_policy!r}"
            )
        # rollback budget for the non-finite-loss policy (counts down
        # across the run; NOT reset per epoch — a persistently-NaN run
        # must eventually abort)
        self._rollback_budget = int(config.rollback_retries)
        self._skipped_steps = 0
        # step-timeline tracer (utils/trace.py): JSONL off unless
        # configured (spans still feed the flight recorder's ring). Every
        # rank writes its OWN file — rank 0 the configured path, rank R
        # `<path>.rankR` — so the trace hub (obs/trace_hub.py) can merge
        # them into one rank-disambiguated Perfetto timeline instead of
        # ranks interleaving torn lines into one file.
        rank = jax.process_index()
        timeline_path = config.timeline_path
        if timeline_path and rank != 0:
            timeline_path = f"{timeline_path}.rank{rank}"
        self.tracer = StepTimeline(timeline_path, rank=rank)
        # flight recorder (obs/flight.py): always-on ring; the dump path
        # defaults under this run's log dir unless the caller/env chose
        # one (bench_multi points it at the leg's artifact)
        flight.set_rank(rank)
        flight.set_default_dump_path(os.path.join(
            config.log_dir, f"flight_{config.method_tag}_rank{rank}.json"
        ))
        # registry counters are process-lifetime; the host-cache gauge
        # needs per-run deltas, so remember where this run started
        self._cache_counted = (0, 0)
        # on-demand device profile over a step range (--profile-steps)
        self._profiling = False
        self.metrics_server = None
        # ONE epoch-persistent decoded-sample cache shared by the train and
        # val loaders (they index the same dataset)
        self.sample_cache = (
            SampleCache(int(config.host_cache_mb) * 2**20)
            if config.host_cache_mb > 0
            else None
        )
        # futures of in-flight async checkpoint writes; drained (and their
        # errors surfaced) when train() ends
        self._ckpt_futures = []
        # per-rank heartbeat (dist/health.py): armed in train() when
        # config.heartbeat_dir is set (the elastic supervisor's failure
        # detector); None otherwise
        self._heartbeat = None

        # model + state
        from distributedpytorch_tpu.models import create_model

        self.model, init_fn = create_model(config)
        params, model_state = init_fn(
            self.rng, (config.image_size[1], config.image_size[0])
        )
        # BatchNorm state threads through the pipeline schedules
        # (parallel/pipeline.py): stage functions apply their segments
        # with mutable batch_stats per microbatch and the stage-axis psum
        # of the deltas reassembles the replicated running stats — no
        # BatchNorm-vs-MP guard anymore.
        lr0 = self.strategy.lr_for(config.learning_rate)
        # the precision policy (ops/precision.py, --dtype) owns the param
        # cast-in and, under bf16_params, wraps the optimizer with f32
        # master weights living in opt_state
        self.policy = self.strategy.policy
        state, self.tx = create_train_state(
            params, lr0, config.weight_decay, model_state=model_state,
            policy=self.policy,
        )
        self.scheduler = ReduceLROnPlateau(
            lr=lr0, patience=config.plateau_patience, factor=config.plateau_factor
        )
        self.start_epoch = 0
        # scalar trainer state that must survive resume (checkpointed as
        # train_meta): --save-best's best metrics, early-stop patience
        self._best_dice = float("-inf")
        self._best_loss = float("inf")
        self._stale_epochs = 0

        if config.checkpoint_name:
            self._restore(config.checkpoint_name, state)
            state = self._restored_state or state

        self.state = self.strategy.place_state(state)

        # data split + loaders (ONE seeded split for every strategy — the
        # deliberate fix of reference quirk 5)
        train_idx, val_idx = seeded_split(
            len(self.dataset), config.val_fraction, seed=0
        )
        if len(val_idx) < config.batch_size and self.strategy.is_main:
            # val loader drops ragged batches (reference train_utils.py:42),
            # so a val split smaller than one batch evaluates NOTHING and
            # val loss/Dice come out NaN — the reference fails the same way,
            # silently; at least say so.
            logger.warning(
                "validation split has %d samples < batch size %d — every "
                "val batch is dropped and val loss/Dice will be NaN; raise "
                "-v/--validation or lower -b",
                len(val_idx), config.batch_size,
            )
        self.train_loader = DataLoader(
            self.dataset,
            indices=train_idx,
            batch_size=config.batch_size,
            shuffle=True,
            drop_last=self.strategy.drop_last_train,
            seed=config.seed,
            shard=self.strategy.data_shard(),
            num_workers=config.num_workers,
            cache=self.sample_cache,
            tracer=self.tracer,
            max_retries=config.data_retries,
            retry_backoff_s=config.retry_backoff_s,
        )
        # Val: drop_last=True (reference train_utils.py:42). The loader is
        # unsharded — batch formation is identical everywhere — but
        # multi-process strategies ASSIGN whole batches round-robin
        # (evaluate_sharded): each process computes 1/world of the val set
        # and every process reads back identical per-batch metrics from
        # the grouped dispatch, so the plateau scheduler stays in lockstep
        # (the reference's rank-divergent lr, quirk 7, cannot happen) with
        # no redundant work.
        self.val_loader = DataLoader(
            self.dataset,
            indices=val_idx,
            batch_size=config.batch_size,
            shuffle=False,
            drop_last=True,
            num_workers=config.num_workers,
            cache=self.sample_cache,
            max_retries=config.data_retries,
            retry_backoff_s=config.retry_backoff_s,
        )

        self.train_step = self.strategy.build_train_step(self.model, self.tx)
        # K>1: fuse K optimizer steps into one dispatch (lax.scan); the
        # single-step path still handles the ragged tail of each epoch.
        self.k_dispatch = max(1, int(config.steps_per_dispatch))
        self.grad_accum = max(1, int(config.grad_accum))
        if config.early_stop_patience < 0:
            raise ValueError(
                f"early_stop_patience must be >= 0 (0 = off), got "
                f"{config.early_stop_patience}"
            )
        if self.k_dispatch > 1 and self.grad_accum > 1:
            raise ValueError(
                "--steps-per-dispatch and --grad-accum both stack loader "
                "batches with conflicting step semantics — choose one"
            )
        if config.nonfinite_policy == "skip" and (
            self.k_dispatch > 1 or self.grad_accum > 1
        ):
            raise ValueError(
                "--nonfinite-policy skip discards one STEP's update, which "
                "a fused dispatch / accumulated step cannot isolate — use "
                "rollback or abort with --steps-per-dispatch/--grad-accum"
            )
        self.multi_step = (
            self.strategy.build_multi_train_step(self.model, self.tx)
            if self.k_dispatch > 1
            else None
        )
        self.accum_step = (
            self.strategy.build_accum_train_step(self.model, self.tx)
            if self.grad_accum > 1
            else None
        )
        self.eval_step = self.strategy.build_eval_step(self.model)
        # grouped variant only where there are processes to share with
        self.grouped_eval_step = (
            self.strategy.build_grouped_eval_step(self.model)
            if self.strategy.eval_shard().world > 1
            else None
        )
        self.records = LossRecords(
            config.method_tag,
            config.loss_dir,
            every=config.metric_every_steps,
            tracer=self.tracer,
            nonfinite_hook=self._on_nonfinite_loss,
        )
        if getattr(self, "_restored_records", None):
            # a resumed run appends to the run's metric history instead of
            # overwriting the loss pickles with only its post-resume rows
            self.records.load_state_dict(self._restored_records)

    # ------------------------------------------------------------------
    def _build_dataset(self):
        if self.config.synthetic_samples > 0:
            from distributedpytorch_tpu.data import SyntheticSegmentationDataset

            return SyntheticSegmentationDataset(
                length=self.config.synthetic_samples,
                newsize=self.config.image_size,
                seed=self.config.seed,
            )
        images = os.path.join(self.config.data_dir, self.config.images_subdir)
        masks = os.path.join(self.config.data_dir, self.config.masks_subdir)
        return build_dataset(images, masks, self.config.image_size)

    def _eval_variables(self):
        """What the eval step consumes: bare params for pure models, the
        full variables dict for stateful ones (running BatchNorm stats)."""
        if self.state.model_state is not None:
            return {
                "params": self.state.params,
                "batch_stats": self.state.model_state,
            }
        return self.state.params

    def _ckpt_path(self, tag: Optional[str] = None) -> str:
        tag = tag or self.config.method_tag
        return os.path.join(self.config.checkpoint_dir, f"{tag}.ckpt")

    def _restore(self, name: str, state):
        """Load a checkpoint by name (reference -c flag, train.py:42-43 —
        with the backslash path bug fixed and full-state resume added).

        Precision-aware (the ckpt-dtype-drift contract, docs/ANALYSIS.md):
        the manifest's ``precision`` entry is peeked BEFORE any target
        structure is built, a checkpoint saved under a different --dtype
        is converted through the policy seams (exact via the f32 master
        weights in either direction), and every restored params tree is
        re-cast loudly when its dtype drifted — never silently retraced.
        """
        from distributedpytorch_tpu.checkpoint import resolve_checkpoint
        from distributedpytorch_tpu.ops.precision import (
            POLICIES,
            convert_checkpoint_state,
            ensure_restored_dtypes,
        )

        path = resolve_checkpoint(name, self.config.checkpoint_dir)
        self._restored_state = None
        self._restored_records = None
        if path.endswith(".pth"):
            # interop: reference-format weights (no optimizer/epoch state)
            from distributedpytorch_tpu.checkpoint import load_weights

            params = load_weights(path, state.params)
            if self.policy.master_weights:
                # weights-only restore under bf16_params: re-seed the
                # optimizer so its f32 master IS the imported weights —
                # the fresh-init master would silently win otherwise
                state = state.replace(opt_state=self.tx.init(params))
            params = ensure_restored_dtypes(
                params, self.policy, f"pth restore {path}"
            )
            self._restored_state = state.replace(params=params)
            logger.info("Loaded reference .pth weights from %s", path)
            return
        from distributedpytorch_tpu.checkpoint import read_payload
        from distributedpytorch_tpu.ops.precision import get_policy

        # ONE file read: the manifest decides the target structures, and
        # the same payload then binds them (a multi-GB checkpoint must
        # not be deserialized twice per resume)
        payload = read_payload(path)
        saved_name = (payload.get("topology") or {}).get("precision")
        if saved_name is None:
            # pre-policy checkpoints carried f32 params + a plain Adam
            # state — structurally the bf16 policy
            saved_policy = POLICIES["bf16"]
        else:
            # unknown names fail LOUDLY (a newer build's policy, a
            # corrupted manifest) — guessing a structure here would die
            # later in an opaque from_state_dict mismatch
            saved_policy = get_policy(saved_name)
        opt_target = state.opt_state
        if saved_policy.master_weights != self.policy.master_weights:
            # the saved opt_state's STRUCTURE differs (the master-weight
            # wrapper nests it) — build the saved-side target to restore
            # into, then convert below
            from distributedpytorch_tpu.ops.optim import adam_l2

            saved_tx = saved_policy.wrap_optimizer(
                adam_l2(self.scheduler.lr, self.config.weight_decay)
            )
            # abstract target: from_state_dict needs only the STRUCTURE,
            # so eval_shape builds it without a host copy of the params
            # or throwaway f32 master/m/v allocations (~3x param bytes
            # on the restore path of a large model)
            opt_target = jax.eval_shape(saved_tx.init, state.params)
        restored = load_checkpoint(
            path, state.params, opt_target, state.model_state,
            payload=payload,
        )
        # params in the SAVED dtype, before the policy conversion casts —
        # the exact master seed for weights-only checkpoints below
        raw_params = restored["params"]
        restored["params"], restored["opt_state"] = convert_checkpoint_state(
            saved_policy,
            self.policy,
            restored["params"],
            restored["opt_state"],
            where=f"restore {path}",
        )
        if restored["opt_state"] is None and self.policy.master_weights:
            # weights-only native checkpoint (no opt_state saved) under a
            # master-weight policy: re-seed the optimizer from the SAVED
            # params so the f32 master IS the restored weights — same
            # hazard the .pth branch guards: the fresh-init master would
            # otherwise revert the params at the first update
            logger.warning(
                "restore %s: checkpoint carries no optimizer state — "
                "re-seeding the %r master weights from the restored "
                "params", path, self.policy.name,
            )
            restored["opt_state"] = self.tx.init(raw_params)
        # Mesh-resharding restore (docs/RELIABILITY.md "Elastic runs"):
        # checkpoints hold FULL host arrays (every sharded leaf was
        # allgathered at save time), so restoring under a DIFFERENT
        # topology — N→M processes after an elastic shrink, another
        # strategy's mesh shape — just re-places them under the current
        # sharding (place_state). Say so when it happens: a silent
        # layout change is the kind of thing a post-incident reader
        # needs one grep to find.
        saved_topo = restored.get("topology")
        if saved_topo is not None:
            from distributedpytorch_tpu.checkpoint import save_topology

            current_topo = {**save_topology(), **self.strategy.topology()}
            # a --dtype change is a precision conversion, not a mesh
            # reshard — convert_checkpoint_state logged it above
            current_topo.pop("precision", None)
            if {k: saved_topo.get(k) for k in current_topo} != current_topo:
                logger.warning(
                    "mesh-resharding restore: checkpoint saved under %s, "
                    "restoring onto %s — gathered host arrays re-placed "
                    "under the current mesh",
                    saved_topo, current_topo,
                )
        new_state = state.replace(params=restored["params"], step=restored["step"])
        if restored["opt_state"] is not None:
            new_state = new_state.replace(opt_state=restored["opt_state"])
        if restored["model_state"] is not None:
            new_state = new_state.replace(model_state=restored["model_state"])
        if restored["scheduler"]:
            self.scheduler.load_state_dict(restored["scheduler"])
            new_state = new_state.replace(
                opt_state=set_learning_rate(new_state.opt_state, self.scheduler.lr)
            )
        self.start_epoch = restored["epoch"]
        meta = restored.get("train_meta") or {}
        self._best_dice = float(meta.get("best_dice", float("-inf")))
        self._best_loss = float(meta.get("best_loss", float("inf")))
        self._stale_epochs = int(meta.get("stale_epochs", 0))
        self._restored_state = new_state
        self._restored_records = restored.get("records")
        logger.info("Resumed from %s at epoch %d", path, self.start_epoch)

    def _save_needs_all_ranks(self) -> bool:
        """True iff the checkpoint snapshot is a COLLECTIVE: some state
        leaf is sharded across processes (FSDP/TP pods), so every rank
        must participate in its allgather. Replicated-state strategies
        (DDP) answer False and non-main ranks skip the payload build
        entirely — a full-tree device_get per epoch is seconds of pure
        waste on a tunneled runtime. Identical on every rank (the
        sharding layout is), so the skip cannot desync collectives;
        memoized — the layout is fixed for the trainer's lifetime."""
        cached = getattr(self, "_save_collective_memo", None)
        if cached is not None:
            return cached
        if jax.process_count() == 1:
            result = False
        else:
            from distributedpytorch_tpu.checkpoint import (
                needs_collective_gather,
            )

            result = any(
                needs_collective_gather(x)
                for x in jax.tree.leaves(
                    (self.state.params, self.state.opt_state,
                     self.state.model_state)
                )
            )
        self._save_collective_memo = result
        return result

    def _save(self, epoch: int) -> None:
        # dedup on EVERY rank (the decision is epoch-driven, identical
        # everywhere). No blanket is_main gate: when state is sharded
        # across processes the host snapshot inside the save is a
        # COLLECTIVE allgather, so all ranks must reach it in lockstep —
        # but for replicated state non-main ranks have nothing to
        # contribute and skip the (expensive) payload build; the file
        # write itself is always rank-0-gated (_save_tagged).
        if epoch == getattr(self, "_last_saved_epoch", None):
            return
        self._last_saved_epoch = epoch
        if not self.strategy.is_main and not self._save_needs_all_ranks():
            return
        self._save_tagged(self._ckpt_path(), epoch)

    def _save_tagged(self, path: str, epoch: int) -> None:
        """One checkpoint save — async (host snapshot inline, serialize +
        write on the background writer) unless config.async_checkpoint is
        off. Every rank builds the payload (collective when sharded — see
        _save); only the main process writes the file, retaining the
        newest config.keep_checkpoints copies. Async futures are drained
        when train() ends, so the file is durable before anything outside
        the run can read it."""
        if self.config.async_checkpoint:
            # surface a failed EARLIER write now, not at the end of the
            # run (a disk-full at epoch 1 of 100 must not let 99 epochs
            # believe their checkpoints are landing), and bound the queue:
            # with >2 writes still in flight the filesystem is stalled —
            # block on the oldest (the synchronous behavior) rather than
            # accumulate full-model payloads in RAM without limit
            for fut in [f for f in self._ckpt_futures if f.done()]:
                self._ckpt_futures.remove(fut)
                fut.result()  # raises if the write failed
            while len(self._ckpt_futures) > 2:
                self._ckpt_futures.pop(0).result()
        flight.record("phase", name="checkpoint", epoch=epoch)
        save_fn = (
            save_checkpoint_async
            if self.config.async_checkpoint
            else save_checkpoint
        )
        fut = save_fn(
            path,
            self.state.params,
            self.state.opt_state,
            self.scheduler.state_dict(),
            step=int(self.state.step),
            epoch=epoch,
            records_state=self.records.state_dict(),
            model_state=self.state.model_state,
            train_meta=self._train_meta(),
            keep=self.config.keep_checkpoints,
            write=self.strategy.is_main,
            topology=self.strategy.topology(),
        )
        if fut is not None:
            self._ckpt_futures.append(fut)

    def _drain_checkpoint_futures(self, raise_errors: bool) -> None:
        """Block until every queued async checkpoint write is on disk.
        Write errors re-raise when asked (normal exit) and are logged
        otherwise (already unwinding another exception — masking it with
        a secondary I/O error would hide the real failure)."""
        futures, self._ckpt_futures = self._ckpt_futures, []
        first_exc = None
        for fut in futures:
            try:
                fut.result()
            except Exception as exc:  # noqa: BLE001 — surfaced below
                logger.exception("async checkpoint write failed")
                first_exc = first_exc or exc
        if first_exc is not None and raise_errors:
            raise first_exc

    def _train_meta(self) -> dict:
        return {
            "best_dice": self._best_dice,
            "best_loss": self._best_loss,
            "stale_epochs": self._stale_epochs,
        }

    # -- step-level failure policies (docs/RELIABILITY.md) -------------------
    def _finite_agreed(self, loss) -> bool:
        """Policy ``skip``'s per-step finiteness check, made COLLECTIVE
        on multi-process meshes: a non-finite loss can be rank-local (a
        hardware bitflip on one chip, an injected ``nan_loss@R``), and a
        rank that discards its update while its peers apply theirs has
        silently forked the replicas — the exact divergence the policy
        exists to prevent. One tiny allgather per step, only under
        ``skip`` (which already pays a per-step host sync) and only with
        >1 process; ANY rank non-finite → every rank discards."""
        finite = bool(np.isfinite(float(loss)))
        if jax.process_count() == 1:
            return finite
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([0 if finite else 1], np.int32)
        )
        return not bool(np.any(flags))

    def _on_nonfinite_loss(self, step: int, value: float) -> None:
        """LossRecords' readback hook: a train loss drained to host came
        back NaN/Inf. Free on healthy runs — detection rides the drain
        the metrics pipeline already does. ``skip`` handles non-finite
        steps synchronously in the loop (run_one), so reaching this hook
        under it only happens for paths skip cannot guard; log, don't
        kill. ``abort``/``rollback`` raise — the epoch loop catches for
        rollback, everything else propagates."""
        if self.config.nonfinite_policy == "skip":
            logger.warning(
                "non-finite loss %s at step %d reached the metrics drain "
                "under policy 'skip' (unguarded path) — continuing", value, step,
            )
            return
        raise NonFiniteLossError(
            f"non-finite train loss {value} at step {step} "
            f"(policy={self.config.nonfinite_policy})"
        )

    def _try_rollback(self, exc: Exception) -> bool:
        """``rollback`` policy: reload the newest intact checkpoint
        in-place (state, scheduler, metric history, epoch) and let the
        epoch loop redo from there. False = cannot roll back (wrong
        policy, budget exhausted, or nothing to restore) — the caller
        re-raises."""
        cfg = self.config
        if cfg.nonfinite_policy != "rollback":
            return False
        if jax.process_count() > 1:
            # in-place rollback is single-process only, like
            # fit_with_restarts' restarts: ranks would race rank 0's
            # in-flight write/rotate (non-main ranks have no futures to
            # drain) and could restore DIFFERENT epochs — divergent
            # collective programs, deadlocked job. Abort instead; the
            # launcher's restart loop re-rendezvouses all ranks against
            # a settled checkpoint file.
            logger.error(
                "rollback policy is single-process; multi-process runs "
                "abort and rely on the launcher's restart loop"
            )
            return False
        if self._rollback_budget <= 0:
            logger.error(
                "rollback budget exhausted (%d rollbacks used) — aborting",
                cfg.rollback_retries,
            )
            return False
        # the checkpoint we are about to read may still be queued on the
        # background writer — make it durable first
        self._drain_checkpoint_futures(raise_errors=False)
        path = self._ckpt_path()
        from distributedpytorch_tpu.checkpoint import retained_checkpoints

        # any retained candidate will do — load_checkpoint's fallback
        # walks the chain, and a crash between rotate and rename can
        # leave only `path.1` on disk with the live slot empty
        if not retained_checkpoints(path):
            logger.error("rollback requested but no checkpoint at %s", path)
            return False
        self._rollback_budget -= 1
        obsm.TRAIN_ROLLBACKS.inc()
        flight.record("rollback", error=str(exc)[:200],
                      retries_left=self._rollback_budget)
        logger.warning(
            "%s — rolling back to %s (%d retries left)",
            exc, path, self._rollback_budget,
        )
        self._restore(cfg.method_tag, self.state)
        self.state = self.strategy.place_state(self._restored_state)
        if self._restored_records:
            self.records.load_state_dict(self._restored_records)
        else:  # pre-records checkpoint: drop the poisoned history
            self.records = LossRecords(
                cfg.method_tag,
                cfg.loss_dir,
                every=cfg.metric_every_steps,
                tracer=self.tracer,
                nonfinite_hook=self._on_nonfinite_loss,
            )
        self._last_saved_epoch = None
        return True

    def _watchdog_timeout(self) -> None:
        """StepWatchdog expiry (watchdog thread): dump the step-timeline
        tracer's per-phase spans AND the flight recorder's ring for
        diagnosis, then request a checkpoint-and-stop through the same
        collective agreement the signal handler uses. Best-effort by
        nature — a host truly wedged inside a native call cannot
        checkpoint; the dumps are then the run's last diagnostic."""
        summary = {
            k: v for k, v in self.tracer.summary().items() if v is not None
        }
        logger.error(
            "dispatch watchdog: step loop made no progress for %.1fs — "
            "requesting checkpoint-and-stop. Per-phase timeline: %s",
            self.config.step_timeout_s,
            json.dumps(summary) if summary else "(no spans recorded)",
        )
        recent = self.tracer.events()[-24:]
        if recent:
            logger.error("recent timeline spans: %s", json.dumps(recent))
        elif not self.tracer.enabled:
            logger.error(
                "step-timeline tracing is off — run with --trace-timeline "
                "to capture per-phase spans for watchdog diagnosis"
            )
        self.tracer.flush()
        # the post-mortem artifact: the ring's tail identifies the phase
        # the loop wedged in (docs/OBSERVABILITY.md lifecycle)
        flight.dump(
            "watchdog_timeout",
            extra={"step_timeout_s": self.config.step_timeout_s,
                   "timeline_summary": summary},
        )
        self._stop_requested = True

    def _profile_tick(self, global_step: int) -> None:
        """--profile-steps N:M — start the jax.profiler device trace
        entering step N+1, stop once step M has run. Two integer
        compares per iteration when armed; rank 0 only (one profile per
        run, like the whole-run --profile-dir capture)."""
        lo, hi = self.config.profile_steps
        if not self._profiling and lo <= global_step < hi:
            out = self.config.profile_dir or os.path.join(
                self.config.log_dir, "profile"
            )
            logger.info(
                "profiler: capturing device trace for steps [%d, %d) → %s",
                lo, hi, out,
            )
            jax.profiler.start_trace(out)
            self._profiling = True
            flight.record("profile", action="start", step=global_step)
        elif self._profiling and global_step >= hi:
            jax.profiler.stop_trace()
            self._profiling = False
            flight.record("profile", action="stop", step=global_step)

    def _update_cache_metrics(self) -> None:
        """Epoch-boundary host-cache accounting: registry counters get
        the per-run delta (they are process-lifetime), the gauge gets
        the run's hit rate."""
        if self.sample_cache is None:
            return
        hits, misses = self.sample_cache.hits, self.sample_cache.misses
        h0, m0 = self._cache_counted
        if hits > h0:
            obsm.CACHE_HITS.inc(hits - h0)
        if misses > m0:
            obsm.CACHE_MISSES.inc(misses - m0)
        self._cache_counted = (hits, misses)
        total = hits + misses
        if total:
            obsm.CACHE_HIT_RATIO.set(hits / total)

    # ------------------------------------------------------------------
    def _record(self, loss, n_imgs: int, global_step: int, pbar) -> None:
        rows_before = len(self.records.train_rows)
        self.records.record_train(global_step, loss, n_imgs)
        pbar.update(n_imgs)
        if len(self.records.train_rows) > rows_before:
            pbar.set_postfix(loss=f"{self.records.train_rows[-1][2]:.4f}")

    def _install_signal_handler(self):
        """Failure detection the reference lacks (SURVEY.md §5: a mid-run
        crash loses everything): on SIGTERM/SIGINT, finish the in-flight
        step, checkpoint full state, then exit — so preemption (the normal
        way TPU jobs die) costs at most one epoch of progress, resumable
        via ``-c <method>``.

        Signal handlers are main-thread-only; if train() runs on another
        thread the install fails and this feature is simply OFF (signals
        then take their default action — no graceful checkpoint).

        Multi-process runs stop only at epoch boundaries, and only by
        AGREEMENT (`_stop_agreed` allgathers the flag): a rank that broke
        out mid-epoch on a local signal would abandon the collectives its
        peers' jitted steps are waiting on and hang the job.
        """
        self._stop_requested = False
        self._prev_handlers = {}

        def request_stop(signum, frame):
            self._stop_requested = True
            # the preemption post-mortem: what the run was doing when the
            # scheduler pulled the plug (dump is never-raises by contract)
            flight.record("signal", signum=int(signum))
            flight.dump("sigterm" if signum == signal.SIGTERM else
                        f"signal_{int(signum)}")
            logger.info(
                "Signal %d: will checkpoint and stop at the next step", signum
            )

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.signal(sig, request_stop)
            except ValueError:  # not in main thread — feature unavailable
                pass

    def _restore_signal_handler(self):
        for sig, handler in self._prev_handlers.items():
            signal.signal(sig, handler)

    def _stop_agreed(self, global_step: int = -1) -> bool:
        """Collective stop decision: True iff ANY process saw a signal.
        One tiny allgather per epoch — never called per step.

        The same allgather carries each rank's step counter — the
        cross-rank step-agreement check of the elastic health layer
        (dist/health.py): ranks that reach this epoch boundary at
        DIFFERENT global steps are executing divergent programs (a
        skipped update that wasn't agreed, a loader desync), which
        would otherwise surface as replica drift or a wedged collective
        far from the cause. On divergence every rank sees the same
        allgathered evidence, so all mark their beat ``desynced`` (the
        supervisor's classifier keys on it), log ONE line, and stop
        together — an agreed teardown instead of a hang."""
        if jax.process_count() == 1:
            return self._stop_requested
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray(
                [1 if self._stop_requested else 0, int(global_step)],
                np.int32,
            )
        )
        steps = flags[:, 1]
        if global_step >= 0 and len(set(int(s) for s in steps)) > 1:
            logger.error(
                "rank %d: desynced at step agreement — per-rank steps %s",
                jax.process_index(), [int(s) for s in steps],
            )
            if self._heartbeat is not None:
                self._heartbeat.mark("desynced")
            return True
        return bool(np.any(flags[:, 0]))

    def train(self) -> dict:
        """Run the configured epochs; signal handlers are scoped to the run
        (try/finally: an exception mid-epoch must not leave the process
        uninterruptible). Every queued async checkpoint write is drained
        before returning OR raising — a crash-restart rebuilds the next
        Trainer from the checkpoint file, which must be fully on disk by
        then."""
        self._install_signal_handler()
        if self.config.heartbeat_dir:
            from distributedpytorch_tpu.dist.health import Heartbeat

            self._heartbeat = Heartbeat(
                self.config.heartbeat_dir,
                jax.process_index(),
                self.config.heartbeat_interval_s,
            ).start()
            self._heartbeat.update(self.start_epoch, int(self.state.step))
        if self.config.metrics_port is not None:
            from distributedpytorch_tpu.obs.http import (
                build_fingerprint,
                start_metrics_server,
            )

            # rank R binds port+R so every rank of a multi-process job is
            # its own scrape target; port 0 stays 0 (ephemeral — tests)
            port = self.config.metrics_port
            if port:
                port += jax.process_index()
            self.metrics_server = start_metrics_server(
                port, fingerprint=build_fingerprint(self.config)
            )
            logger.info("metrics: serving /metrics on port %d",
                        self.metrics_server.port)
        ok = False
        try:
            result = self._run()
            ok = True
            return result
        finally:
            self._restore_signal_handler()
            if getattr(self, "_watchdog", None) is not None:
                self._watchdog.stop()
            if self._profiling:  # run ended inside the --profile-steps range
                try:
                    jax.profiler.stop_trace()
                finally:
                    self._profiling = False
            if self.metrics_server is not None:
                self.metrics_server.close()
            try:
                # flush BEFORE draining checkpoints: a failed write
                # raises out of the drain, and the final epoch's
                # timeline spans are most valuable exactly when
                # diagnosing that failing run
                self.tracer.flush()
                if self._heartbeat is not None:
                    # keep BEATING through the final drain (a long last
                    # write must not read as a frozen process to the
                    # supervisor's beat-age rule) but leave steady-state
                    # timing: the drain makes no step progress and must
                    # not trip the progress-timeout hang rule either
                    self._heartbeat.timed = False
                # the final drain is a HARD error boundary on a clean
                # run: a failed write of the LAST save has no "next
                # save" to surface it, so it must raise here, out of
                # train() itself
                self._drain_checkpoint_futures(raise_errors=ok)
            finally:
                if self._heartbeat is not None:
                    self._heartbeat.stop()

    def _run(self) -> dict:
        cfg = self.config
        n_train = self.train_loader.num_samples()
        logger.info(
            "Training %s: %d epochs, global batch %d, lr %.2e, %d train batches/shard",
            cfg.train_method,
            cfg.epochs,
            self.strategy.global_batch_size,
            get_learning_rate(self.state.opt_state),
            len(self.train_loader),
        )
        # whole-run capture only when no step range was asked for — the
        # two would race one another's start/stop on the same profiler
        whole_run_profile = (
            cfg.profile_dir and cfg.profile_steps is None
            and self.strategy.is_main
        )
        profile_by_steps = cfg.profile_steps is not None and self.strategy.is_main
        if whole_run_profile:
            jax.profiler.start_trace(cfg.profile_dir)

        from tqdm import tqdm

        global_step = int(self.state.step)
        val_loss = float("nan")
        val_dice = float("nan")
        stopped_early = False
        skip_guard = cfg.nonfinite_policy == "skip"
        # dispatch watchdog (docs/RELIABILITY.md): armed per step-loop
        # iteration, paused across the non-step phases (eval, end-of-epoch
        # checkpointing) whose duration is unrelated to step health;
        # stopped in train()'s finally
        self._watchdog = None
        if cfg.step_timeout_s > 0:
            self._watchdog = StepWatchdog(
                cfg.step_timeout_s, self._watchdog_timeout
            )
            self._watchdog.start()
        watchdog = self._watchdog
        # while, not for: the rollback policy rewinds `epoch` to the
        # restored checkpoint mid-run (NonFiniteLossError handler below).
        # `untimed_epoch` pins the FIRST executed epoch (where every
        # executable shape compiles) for the watchdog exemption — it
        # deliberately does NOT follow a rollback's start_epoch rewind:
        # redone epochs run on warm executables and stay watched.
        epoch = self.start_epoch
        untimed_epoch = self.start_epoch
        while epoch < cfg.epochs:
            try:
                # tqdm parity (reference train_utils.py:57): per-epoch image
                # bar, main process only. Postfix shows the mean-of-last-10
                # row loss — NOT the per-step loss, which would force a
                # device sync per step. exact images this epoch will yield:
                # drop_last trims the ragged tail, otherwise every shard
                # sample appears exactly once
                with tqdm(
                    total=min(n_train, len(self.train_loader) * cfg.batch_size),
                    desc=f"Epoch {epoch + 1}/{cfg.epochs}",
                    unit="img",
                    disable=not self.strategy.is_main,
                    leave=False,
                ) as pbar:
                    def run_one(batch, placed=None):
                        nonlocal global_step
                        n_imgs = batch["image"].shape[0]
                        if placed is None:
                            placed = self.strategy.place_batch(batch)
                        # policy 'skip' holds the pre-step state so a
                        # non-finite step's update can be discarded
                        # (donation is off under it — _state_donation)
                        prev_state = self.state if skip_guard else None
                        with self.tracer.span("dispatch", step=global_step + 1):
                            self.state, loss = self.train_step(self.state, placed)
                        if faults.fire("nan_loss", epoch=epoch,
                                       step=global_step + 1):
                            loss = float("nan")  # forced step output
                        if skip_guard and not self._finite_agreed(loss):
                            # the one host sync per step this policy costs
                            self._skipped_steps += 1
                            obsm.TRAIN_SKIPPED_STEPS.inc()
                            logger.warning(
                                "non-finite loss at step %d: update "
                                "discarded (%d skipped so far)",
                                global_step + 1, self._skipped_steps,
                            )
                            self.state = prev_state
                            return
                        global_step += 1
                        # loss stays a device scalar; LossRecords drains it
                        # to host only at the next row/flush boundary
                        self._record(loss, n_imgs, global_step, pbar)

                    def run_stack(buffered, placed):
                        nonlocal global_step
                        with self.tracer.span(
                            "dispatch", step=global_step + 1, k=len(buffered)
                        ):
                            self.state, losses = self.multi_step(self.state, placed)
                        # ONE memoized device→host pull for the whole (K,)
                        # loss array, and only when a metrics row actually
                        # needs it — slicing losses[i] here would issue K
                        # extra dispatches and forfeit the amortization
                        # this path exists for.
                        memo = {}

                        def lazy(i):
                            def pull():
                                if "host" not in memo:
                                    memo["host"] = np.asarray(losses)
                                return memo["host"][i]

                            # LossRecords' non-blocking drain starts an
                            # async host copy when a row is parked; expose
                            # the (K,) array's hook so the fused-dispatch
                            # path gets the same early D2H streaming as
                            # plain device scalars
                            pull.copy_to_host_async = losses.copy_to_host_async
                            return pull

                        for i, b in enumerate(buffered):
                            global_step += 1
                            self._record(lazy(i), b["image"].shape[0], global_step, pbar)

                    def run_accum(buffered, placed):
                        # ONE optimizer step over the K stacked batches —
                        # effective batch K·b, exact loss (make_accum_train_step)
                        nonlocal global_step
                        with self.tracer.span(
                            "dispatch", step=global_step + 1, k=len(buffered)
                        ):
                            self.state, loss = self.accum_step(self.state, placed)
                        global_step += 1
                        self._record(
                            loss,
                            sum(b["image"].shape[0] for b in buffered),
                            global_step,
                            pbar,
                        )

                    stacking = self.multi_step is not None or self.accum_step is not None
                    stack_size = (
                        self.k_dispatch if self.multi_step is not None else self.grad_accum
                    )
                    run_buffered = (
                        run_stack if self.multi_step is not None else run_accum
                    )
                    single_process = jax.process_count() == 1
                    # The async step pipeline (utils/prefetch.py): the
                    # epoch's batch stream becomes SINGLE/STACK work items
                    # whose np.stack + device placement run on the prefetch
                    # worker, `prefetch_batches` payloads ahead of this
                    # loop — batch N+1's H2D rides under batch N's
                    # executing dispatch. Depth 0 degrades to inline
                    # placement (the synchronous baseline; identical loss
                    # sequence either way).
                    source = pipelined_placement(
                        stacked_work(
                            self.train_loader.epoch_batches(epoch),
                            stack_size if stacking else 1,
                            cfg.batch_size,
                        ),
                        self.strategy.place_work,
                        depth=cfg.prefetch_batches,
                        tracer=self.tracer,
                        epoch=epoch,
                        max_retries=cfg.data_retries,
                        retry_backoff_s=cfg.retry_backoff_s,
                    )
                    # closing(): breaking out mid-epoch (signal stop) must
                    # CLOSE the pipeline generator so its worker stops and
                    # queued device-placed payloads get released — GC-time
                    # cleanup would keep them pinned through the checkpoint
                    # save. Work items past the stop (including a partial
                    # group's drained singles) are simply never stepped:
                    # they were never trained, so skipping them loses
                    # nothing, and a preemption grace window may be ticking.
                    flight.record("phase", name="epoch_start", epoch=epoch,
                                  step=global_step)
                    # host-observed step cadence → the step-time histogram
                    # (a perf_counter read + one bounded observe per
                    # iteration; no device sync)
                    iter_t0 = None
                    with contextlib.closing(source):
                        for (kind, payload), placed in source:
                            now_t = time.perf_counter()
                            if iter_t0 is not None:
                                obsm.TRAIN_STEP_SECONDS.observe(
                                    now_t - iter_t0
                                )
                            iter_t0 = now_t
                            if profile_by_steps:
                                self._profile_tick(global_step)
                            if self._heartbeat is not None:
                                # attribute assignments only — the beat
                                # FILE is written by the heartbeat's own
                                # thread (dist/health.py): nothing here
                                # blocks or syncs. `timed` mirrors the
                                # watchdog's first-executed-epoch
                                # exemption: the supervisor's
                                # progress-timeout hang verdict applies
                                # only in steady state.
                                self._heartbeat.timed = epoch != untimed_epoch
                                self._heartbeat.update(epoch, global_step)
                            if watchdog is not None:
                                if epoch == untimed_epoch:
                                    # the first executed epoch compiles
                                    # every executable shape (initial
                                    # step, K-stack, ragged tail) —
                                    # minutes on a tunneled runtime; an
                                    # armed deadline here would fire on
                                    # a healthy compile. Untimed by
                                    # design; steady-state epochs arm.
                                    watchdog.pause()
                                else:
                                    watchdog.pet()
                            # mid-epoch stop is single-process only: in
                            # multi-process runs ranks must agree (epoch
                            # boundary) or collectives desync and hang —
                            # see _install_signal_handler
                            if self._stop_requested and single_process:
                                break
                            if kind == "single":
                                run_one(payload, placed)
                            else:
                                run_buffered(payload, placed)
                            # simulated preemption: deliver a real SIGTERM
                            # through the installed handler so the drill
                            # exercises the production stop path
                            if faults.fire("sigterm", epoch=epoch,
                                           step=global_step):
                                signal.raise_signal(signal.SIGTERM)
                            # elastic chaos sites (docs/RELIABILITY.md
                            # "Elastic runs"): kill or wedge THIS rank
                            # mid-epoch, exactly how a preempted or
                            # stuck peer presents to the supervisor's
                            # health classifier. rank_kill is a real
                            # SIGKILL — no handler, no checkpoint, no
                            # atexit: the survivors' collectives are
                            # genuinely abandoned.
                            if faults.fire("rank_kill", epoch=epoch,
                                           step=global_step):
                                logger.error(
                                    "injected rank_kill: SIGKILL rank %d "
                                    "(pid %d) at %d:%d",
                                    jax.process_index(), os.getpid(),
                                    epoch, global_step,
                                )
                                os.kill(os.getpid(), signal.SIGKILL)
                            if faults.fire("rank_hang", epoch=epoch,
                                           step=global_step):
                                hang_s = float(
                                    os.environ.get("DPT_FAULT_HANG_S", "3600")
                                )
                                logger.error(
                                    "injected rank_hang: rank %d step loop "
                                    "sleeping %.0fs at %d:%d",
                                    jax.process_index(), hang_s,
                                    epoch, global_step,
                                )
                                time.sleep(hang_s)
                if watchdog is not None:
                    watchdog.pause()
                if self._heartbeat is not None:
                    # epoch boundary: beats keep moving through the
                    # (non-step) eval/checkpoint phases
                    self._heartbeat.update(epoch, global_step)

                if self._stop_agreed(global_step):
                    # save a resumable snapshot at the last COMPLETED epoch
                    # — resume redoes the interrupted epoch from its start
                    # (the dedup guard is cleared: mid-epoch params/opt
                    # state are newer than the end-of-previous-epoch save
                    # of same index)
                    self._last_saved_epoch = None
                    self._save(epoch)
                    logger.info(
                        "Stopped by signal at epoch %d step %d; checkpoint saved",
                        epoch + 1,
                        global_step,
                    )
                    break

                flight.record("phase", name="eval", epoch=epoch,
                              step=global_step)
                if self.grouped_eval_step is not None:
                    val_loss, val_dice = evaluate_sharded(
                        self.eval_step,
                        self.grouped_eval_step,
                        self._eval_variables(),
                        self.val_loader,
                        self.strategy.place_batch,
                        self.strategy.eval_shard(),
                        progress=self.strategy.is_main,
                    )
                else:
                    val_loss, val_dice = evaluate(
                        self.eval_step,
                        self._eval_variables(),
                        self.val_loader,
                        self.strategy.place_batch,
                        progress=self.strategy.is_main,
                    )
                self.records.record_val(global_step, val_loss, val_dice)
                new_lr = self.scheduler.step(val_loss)
                # float32 state vs python float: compare with tolerance
                if not np.isclose(new_lr, get_learning_rate(self.state.opt_state), rtol=1e-6):
                    logger.info("Epoch %d: plateau → lr %.3e", epoch + 1, new_lr)
                    self.state = self.state.replace(
                        opt_state=set_learning_rate(self.state.opt_state, new_lr)
                    )
                logger.info(
                    "Epoch %d/%d: val loss %.4f, val dice %.4f (%.1f imgs/s)",
                    epoch + 1,
                    cfg.epochs,
                    val_loss,
                    val_dice,
                    self.records.images_per_second(),
                )
                # append this epoch's timeline spans (no-op when tracing is off)
                self.tracer.flush()
                self._update_cache_metrics()
                # no is_main gate: val_dice is identical on every rank, so
                # all ranks take this branch together — the payload build
                # inside _save_tagged is collective on sharded state, and
                # the file write is rank-0-gated there
                if cfg.save_best and val_dice > self._best_dice:
                    self._best_dice = val_dice
                    if self.strategy.is_main or self._save_needs_all_ranks():
                        self._save_tagged(
                            self._ckpt_path(f"{cfg.method_tag}_best"), epoch + 1
                        )
                    logger.info(
                        "New best val Dice %.4f at epoch %d → %s",
                        val_dice, epoch + 1, self._ckpt_path(f"{cfg.method_tag}_best"),
                    )
                if cfg.checkpoint_every_epochs and (
                    (epoch + 1) % cfg.checkpoint_every_epochs == 0
                ):
                    self._save(epoch + 1)
                if cfg.early_stop_patience:
                    # NaN val loss (empty split) never counts as improvement
                    # — patience running out on no-signal epochs is
                    # deliberate
                    if val_loss < self._best_loss:
                        self._best_loss = val_loss
                        self._stale_epochs = 0
                    else:
                        self._stale_epochs += 1
                        if self._stale_epochs >= cfg.early_stop_patience:
                            logger.info(
                                "Early stop at epoch %d: val loss has not "
                                "improved for %d epochs (best %.4f)",
                                epoch + 1, self._stale_epochs, self._best_loss,
                            )
                            stopped_early = True
                            self._save(epoch + 1)
                            break
            except NonFiniteLossError as exc:
                # the 'rollback' policy: reload the newest intact
                # checkpoint and redo from its epoch (bounded budget —
                # _try_rollback returns False when exhausted and the
                # error propagates like 'abort'). Park the watchdog
                # first: the drain+restore below is not a step, and its
                # duration must not fire a stop that defeats the
                # recovery (it re-arms at the redone epoch's first pet)
                if watchdog is not None:
                    watchdog.pause()
                if not self._try_rollback(exc):
                    # terminal non-finite abort (policy 'abort', or
                    # 'rollback' with its budget spent): ship the
                    # post-mortem before unwinding
                    flight.dump("nonfinite_abort",
                                extra={"error": str(exc)[:200]})
                    raise
                epoch = self.start_epoch  # _restore rewound it
                global_step = int(self.state.step)
                continue
            epoch += 1

        if whole_run_profile:
            jax.profiler.stop_trace()

        if not self._stop_requested and not stopped_early:
            self._save(cfg.epochs)
        if (
            cfg.save_best
            and self.strategy.is_main
            and self._best_dice == float("-inf")
        ):
            logger.warning(
                "--save-best: no epoch produced a finite val Dice "
                "(empty/missing validation split?) — %s was never written",
                self._ckpt_path(f"{cfg.method_tag}_best"),
            )
        if self.strategy.is_main:
            self.records.save()
        return {
            "val_loss": val_loss,
            "val_dice": val_dice,
            "steps": global_step,
            "images_per_second": self.records.images_per_second(),
            "n_train": n_train,
            # resilience accounting (docs/RELIABILITY.md): updates
            # discarded by policy 'skip' and rollbacks consumed
            "skipped_steps": self._skipped_steps,
            "rollbacks": self.config.rollback_retries - self._rollback_budget,
        }


def fit(config: TrainConfig, dataset=None, strategy=None) -> dict:
    """Functional entry: build a Trainer and run it (the reference's
    `fit(model, criterion, ...)` surface, train_utils.py:22)."""
    return Trainer(config, dataset=dataset, strategy=strategy).train()


def fit_with_restarts(
    config: TrainConfig,
    max_restarts: int = 0,
    dataset=None,
    strategy=None,
    return_trainer: bool = False,
):
    """`fit` with crash recovery: on an exception mid-run, rebuild the
    Trainer from the epoch checkpoint THIS run wrote and continue, up to
    ``max_restarts`` times.

    Failure-recovery capability the reference lacks entirely (SURVEY.md §5:
    `torchrun --standalone` with no --max-restarts, checkpoints only at the
    very end — a crash loses everything). Here every epoch checkpoints
    atomically (including the metric history, so the loss curves survive
    the restart), and a restart redoes at most the crashed epoch. A
    checkpoint left behind by some EARLIER invocation is never resumed —
    that would silently turn a crashed fresh run into an instant no-op
    "success". Restarts are single-process only: in a multi-process run,
    ranks cannot re-rendezvous from inside one surviving process — the
    launcher (torchrun --max-restarts, or the pod scheduler) owns that
    loop, and this wrapper simply re-raises for it.

    Returns the result dict, or ``(result, trainer)`` with
    ``return_trainer=True`` (the trainer whose state finished the run —
    e.g. for exporting final weights).
    """
    import dataclasses

    resumable = os.path.join(config.checkpoint_dir, f"{config.method_tag}.ckpt")
    attempt = 0
    saved_this_run = False
    while True:
        trainer = Trainer(config, dataset=dataset, strategy=strategy)
        if attempt > 0 and trainer.start_epoch >= config.epochs:
            # the crash happened AFTER training completed (final checkpoint
            # written, then e.g. records.save() failed); a "restart" would
            # run zero epochs and report NaN metrics as success — surface
            # the real error instead
            raise last_exc
        try:
            result = trainer.train()
            return (result, trainer) if return_trainer else result
        except KeyboardInterrupt:
            raise
        except Exception as exc:
            # clock-free freshness: _last_saved_epoch is set iff THIS
            # attempt actually wrote the checkpoint (mtime-vs-time.time()
            # comparisons break on skewed/coarse filesystem clocks)
            saved_this_run = saved_this_run or (
                getattr(trainer, "_last_saved_epoch", None) is not None
            )
            if (
                attempt >= max_restarts
                or jax.process_count() > 1
                or not saved_this_run
            ):
                raise
            attempt += 1
            last_exc = exc
            logger.exception(
                "Training crashed; restart %d/%d from %s",
                attempt,
                max_restarts,
                resumable,
            )
            # resume from the per-method checkpoint the epoch loop saves
            config = dataclasses.replace(
                config, checkpoint_name=config.method_tag
            )
