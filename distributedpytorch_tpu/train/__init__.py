"""Training subsystem: ONE functional trainer parameterized by a strategy.

The reference has three ~70-line copy-pasted loops (`fit`, `fit_DP`,
`fit_DDP`, reference utils/train_utils.py:22-248); here there is one jitted
train step (train/steps.py), one epoch driver (train/loop.py), and a family
of strategy objects (parallel/) that differ only in mesh + shardings +
process topology. SURVEY.md §7 design stance.
"""

from distributedpytorch_tpu.train.steps import (  # noqa: F401
    TrainState,
    create_train_state,
    make_eval_step,
    make_train_step,
)
from distributedpytorch_tpu.train.loop import (  # noqa: F401
    Trainer,
    fit,
    fit_with_restarts,
)
