"""The single train/eval step pair every strategy jits.

Semantics parity with the reference hot loop (reference
utils/train_utils.py:59-70):

  * forward → BCE − log-dice on the sigmoid probabilities;
  * the backward runs on ``batch_size × loss`` while the RECORDED loss is the
    unscaled value (train_utils.py:67-69) — reference quirk 1, reproduced
    behind ``TrainConfig.faithful_loss_scaling`` (near-no-op under Adam, see
    SURVEY.md §2);
  * masks arrive as integer (B, H, W); the ``unsqueeze(1)`` channel fix-up
    (train_utils.py:61) becomes a trailing-axis expand — applied in EVERY
    strategy, which deliberately fixes the reference's DP crash (quirk 4);
  * Adam update with the lr read from optimizer state (ops/optim.py), so the
    host-side plateau scheduler never recompiles the step.

TPU notes: precision is governed by the session's PrecisionPolicy
(ops/precision.py, ``--dtype``): under ``f32``/``bf16`` the grad is taken
w.r.t. float32 params directly (XLA inserts the compute-dtype casts once at
trace time); under ``bf16_params`` the on-device params are bf16 and the
policy's master-weight optimizer wrapper runs Adam against an f32 master in
optimizer state, with grads stated f32 at the optimizer boundary
(``policy.cast_grads`` — the wgrad contract). The loss is f32 under every
policy (ops/losses.py pins it). Inputs are NHWC.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from distributedpytorch_tpu.ops import precision as precision_ops
from distributedpytorch_tpu.ops.losses import bce_dice_loss, dice_coefficient
from distributedpytorch_tpu.ops.optim import adam_l2
from distributedpytorch_tpu.ops.precision import PrecisionPolicy


@flax.struct.dataclass
class TrainState:
    """Pure-pytree training state (params + Adam state + step counter).

    ``model_state`` carries non-trainable model collections (BatchNorm
    running statistics for stateful models like models/milesial.py); None
    for pure-params models — the default keeps every existing caller and
    checkpoint shape unchanged."""

    params: Any
    opt_state: Any
    step: jax.Array
    model_state: Any = None


def create_train_state(
    params,
    learning_rate: float,
    weight_decay: float = 1e-8,
    model_state=None,
    policy: Optional[PrecisionPolicy] = None,
) -> Tuple[TrainState, optax.GradientTransformation]:
    """Build the TrainState + optimizer under a precision policy.

    ``policy=None`` keeps the historical behavior (params as given, plain
    Adam) — exactly the ``f32``/``bf16`` policies. Under ``bf16_params``
    the params are cast-in to their bf16 on-device storage dtype and the
    optimizer is wrapped with f32 master weights (the master is seeded
    from the params BEFORE the down-cast, so fresh-init and restored f32
    weights lose nothing to the storage dtype)."""
    tx = adam_l2(learning_rate, weight_decay)
    if policy is not None:
        tx = policy.wrap_optimizer(tx)
        # init the (wrapped) optimizer on the FULL-precision params: the
        # master-weight wrapper promotes its copy from what it is given
        opt_state = tx.init(params)
        params = policy.cast_params(params)
    else:
        opt_state = tx.init(params)
    return (
        TrainState(
            params=params,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
            model_state=model_state,
        ),
        tx,
    )


def _prep_mask(mask: jax.Array) -> jax.Array:
    """(B, H, W) integer mask → (B, H, W, 1) float32 target (the reference's
    `.unsqueeze(1)` + `.to(float32)`, train_utils.py:61 — channel-last here)."""
    return mask[..., None].astype(jnp.float32)


def loss_fn(model, params, batch: Dict[str, jax.Array]) -> jax.Array:
    preds = model.apply({"params": params}, batch["image"])
    return bce_dice_loss(preds, _prep_mask(batch["mask"]))


def _make_loss_fns(loss_impl):
    """The (pure, stateful) loss pair with a pluggable ``loss_impl(preds,
    target) -> loss`` — the strategy's hook for routing the training loss
    through the fused Pallas kernel (ops/fused_loss.py); None keeps the
    XLA loss."""
    if loss_impl is None:
        return loss_fn, stateful_loss_fn

    def custom_loss_fn(model, params, batch):
        preds = model.apply({"params": params}, batch["image"])
        return loss_impl(preds, _prep_mask(batch["mask"]))

    def custom_stateful_loss_fn(model, params, model_state, batch):
        preds, updates = model.apply(
            {"params": params, "batch_stats": model_state},
            batch["image"],
            train=True,
            mutable=["batch_stats"],
        )
        return (
            loss_impl(preds, _prep_mask(batch["mask"])),
            updates["batch_stats"],
        )

    return custom_loss_fn, custom_stateful_loss_fn


def is_stateful_model(model) -> bool:
    """Models that carry non-trainable collections (BatchNorm running
    stats) declare ``is_stateful = True`` (models/milesial.py). The one
    definition both the plain steps here and the pipeline schedules
    (parallel/pipeline.py — stateful stage functions) key off."""
    return bool(getattr(model, "is_stateful", False))


_is_stateful = is_stateful_model  # historical internal alias


def stateful_loss_fn(
    model, params, model_state, batch: Dict[str, jax.Array]
) -> Tuple[jax.Array, Any]:
    """Training loss for a stateful model: applies with
    ``mutable=['batch_stats']`` and returns the updated stats as aux.
    Under a sharded batch the statistics XLA computes are global-batch
    statistics — SyncBN semantics for free (models/milesial.py notes)."""
    preds, updates = model.apply(
        {"params": params, "batch_stats": model_state},
        batch["image"],
        train=True,
        mutable=["batch_stats"],
    )
    return bce_dice_loss(preds, _prep_mask(batch["mask"])), updates["batch_stats"]


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    batch_size: int,
    faithful_loss_scaling: bool = True,
    remat: bool = False,
    loss_impl: Callable = None,
    policy: Optional[PrecisionPolicy] = None,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, jax.Array]]:
    """Build the (unjitted) train step; the strategy decides how to jit/shard
    it. Returns ``step(state, batch) -> (state, unscaled_loss)``.

    `remat=True` rematerializes the forward during the backward
    (jax.checkpoint): activations are recomputed instead of stored, cutting
    peak HBM roughly in half for ~1/3 more FLOPs — the TPU-native answer to
    the reference's 7.8 GB-at-batch-4 VRAM wall (modelsummary.txt:72).

    `loss_impl` swaps the loss computation (default: the XLA
    `bce_dice_loss`); strategies pass the fused Pallas loss under
    ``--pallas`` (Strategy._train_loss_impl).

    `policy` is the session's precision policy: under a master-weight
    policy the backward's grads come out in the bf16 param dtype and are
    stated f32 HERE — before the faithful-quirk scaling, so the scale
    multiply never rounds in bf16 (the wgrad contract's step-entry end;
    the optimizer-boundary end lives in the master-weight wrapper).
    """

    grad_scale = float(batch_size) if faithful_loss_scaling else 1.0
    stateful = _is_stateful(model)
    pure_fn, stateful_fn = _make_loss_fns(loss_impl)
    raw_fwd = stateful_fn if stateful else pure_fn
    fwd = jax.checkpoint(raw_fwd, static_argnums=(0,)) if remat else raw_fwd

    def train_step(state: TrainState, batch: Dict[str, jax.Array]):
        # one update body for both model kinds: the pure path carries the
        # (None) model_state through as aux so the optimizer/step logic
        # exists exactly once
        if stateful:
            value_fn = lambda p: fwd(model, p, state.model_state, batch)  # noqa: E731
        else:
            value_fn = lambda p: (fwd(model, p, batch), state.model_state)  # noqa: E731
        (loss, model_state), grads = jax.value_and_grad(value_fn, has_aux=True)(
            state.params
        )
        if policy is not None:
            grads = policy.cast_grads(grads)
        if grad_scale != 1.0:
            # (batch_size * loss).backward() parity, reference train_utils.py:69
            grads = jax.tree.map(lambda g: g * grad_scale, grads)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                params=params,
                opt_state=opt_state,
                step=state.step + 1,
                model_state=model_state,
            ),
            loss,
        )

    return train_step


def make_accum_train_step(
    model,
    tx: optax.GradientTransformation,
    batch_size: int,
    chunks: int,
    faithful_loss_scaling: bool = True,
    remat: bool = False,
    use_pallas: bool = False,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, jax.Array]]:
    """Gradient accumulation: ONE optimizer step over a (K·b) effective
    batch, holding only one b-sized chunk's activations at a time.

    A capability the reference lacks entirely. The subtlety is that this
    framework's loss is NOT chunk-additive: the log-dice term is a ratio
    of whole-batch sums (reference utils/utils.py:18-23), so summing
    per-chunk loss gradients — what naive accumulation does — computes
    the gradient of a DIFFERENT objective (mean of per-chunk losses).
    Exactness comes from the sufficient-statistics decomposition
    (ops/losses.bce_dice_stats):

        pass 1 (scan): accumulate the 4 stats over chunks — forward only;
        combine:       loss = f(Σstats); cotangent c = ∇f(Σstats), a
                       4-vector known only after ALL chunks are seen;
        pass 2 (scan): per-chunk VJP of stats w.r.t. params against c,
                       summed — each chunk's backward runs with the
                       GLOBAL cotangent.

    Cost: one extra forward (~+33% FLOPs over an unachievable one-pass),
    the standard price of exact accumulation under a non-additive loss.
    `batch` is the K-stacked ``{'image': (K,b,H,W,3), 'mask': (K,b,H,W)}``
    (place with `strategy.place_stacked_batch`). Stateful models
    (BatchNorm) are rejected — per-chunk statistics have no single
    faithful semantics; use a data-parallel mesh for large batches there.

    Precision: the stats accumulator is LOSS_DTYPE and the pass-2 grad
    accumulator is WGRAD_DTYPE (ops/precision.py) under EVERY policy —
    under ``bf16_params`` each chunk's VJP emits bf16 leaves and summing
    K of them in bf16 would violate the stated f32 wgrad-accumulation
    contract the pipeline schedules already honor.
    """
    if _is_stateful(model):
        raise ValueError(
            "gradient accumulation supports stateless models only "
            "(BatchNorm statistics are not chunk-decomposable); use a "
            "data-parallel strategy for large effective batches"
        )
    # the faithful quirk scales by the loader's -b value; the equivalent
    # single-big-batch run would pass -b = K·b, so the EFFECTIVE batch is
    # the faithful scale here (matters only through Adam's eps floor and
    # the L2 term — Adam is otherwise scale-invariant)
    grad_scale = float(batch_size * chunks) if faithful_loss_scaling else 1.0
    if use_pallas:
        from distributedpytorch_tpu.ops.fused_loss import bce_dice_stats_fused

        stats_fn = bce_dice_stats_fused
    else:
        from distributedpytorch_tpu.ops.losses import bce_dice_stats

        stats_fn = bce_dice_stats
    from distributedpytorch_tpu.ops.losses import loss_from_stats

    def chunk_stats(params, chunk):
        preds = model.apply({"params": params}, chunk["image"])
        return stats_fn(preds, _prep_mask(chunk["mask"]))

    fwd = jax.checkpoint(chunk_stats) if remat else chunk_stats

    def accum_step(state: TrainState, stacked: Dict[str, jax.Array]):
        k = stacked["image"].shape[0]
        if k != chunks:
            raise ValueError(
                f"stacked batch carries {k} chunks but this step was built "
                f"for grad_accum={chunks}"
            )
        params = state.params

        def pass1(carry, chunk):
            return carry + fwd(params, chunk), None

        stats, _ = jax.lax.scan(
            pass1, jnp.zeros((4,), precision_ops.LOSS_DTYPE), stacked
        )
        loss, ct = jax.value_and_grad(loss_from_stats)(stats)

        def pass2(carry, chunk):
            _, vjp = jax.vjp(lambda p: fwd(p, chunk), params)
            (g,) = vjp(ct)
            return (
                jax.tree.map(
                    lambda a, x: a + x.astype(precision_ops.WGRAD_DTYPE),
                    carry, g,
                ),
                None,
            )

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, precision_ops.WGRAD_DTYPE), params
        )
        grads, _ = jax.lax.scan(pass2, zeros, stacked)
        if grad_scale != 1.0:
            grads = jax.tree.map(lambda g: g * grad_scale, grads)
        updates, opt_state = tx.update(grads, state.opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return (
            TrainState(
                params=new_params,
                opt_state=opt_state,
                step=state.step + 1,
                model_state=state.model_state,
            ),
            loss,
        )

    return accum_step


def make_multi_train_step(
    step: Callable,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, jax.Array]]:
    """Scan `step` over a leading steps axis in ONE XLA executable.

    ``batches`` is the per-step batch stacked to ``{'image': (K,B,H,W,3),
    'mask': (K,B,H,W)}``; returns ``(state, losses (K,))``. Semantically
    identical to K separate `step` calls on the same data, but the runtime
    dispatches once per K steps instead of once per step — on a remote or
    tunneled PJRT runtime per-dispatch latency otherwise dominates the step
    time (measured: ~50 ms/dispatch over this image's TPU relay, >10× the
    chip's compute time for the reference config).
    """

    def multi_step(state: TrainState, batches: Dict[str, jax.Array]):
        return jax.lax.scan(step, state, batches)

    return multi_step


def grouped_eval_metrics(
    preds: jax.Array, target: jax.Array, groups: int
) -> Dict[str, jax.Array]:
    """Per-group {loss (G,), dice (G,)} of a (G·b, ...) prediction stack.

    Group g's metrics are EXACTLY what `bce_dice_loss`/`dice_coefficient`
    return on that b-sized batch alone — same reduction shapes, same
    order — so G reference-semantics val batches evaluate in ONE dispatch.
    Under a batch sharded over a 'data' mesh axis the leading reshape is a
    split along the sharded axis: each shard computes its own group's
    metrics with no cross-device traffic until the tiny (G,) outputs.
    This is how multi-process eval divides the val set (VERDICT r03
    next-4): process p feeds its own batch as shard p and every process
    reads back the same per-batch values.
    """
    p = preds.reshape((groups, -1) + preds.shape[1:])
    t = target.reshape((groups, -1) + target.shape[1:])
    losses, dices = jax.vmap(
        lambda pp, tt: (bce_dice_loss(pp, tt), dice_coefficient(pp, tt))
    )(p, t)
    return {"loss": losses, "dice": dices}


def make_eval_step(
    model, use_pallas: bool = False, groups: int = 1
) -> Callable[[Any, Dict[str, jax.Array]], Dict[str, jax.Array]]:
    """Eval step: per-batch mean loss (reference evaluate.py:16-19) plus the
    hard-Dice metric the reference never computes (SURVEY.md §2 quirk 6).

    `use_pallas` computes loss AND hard-Dice from the fused one-pass
    Pallas stats kernel (ops/pallas_kernels.py) — same formulas, equal to
    the XLA path within summation-order tolerance (~1e-5 relative).
    Eval-only: the train loss stays XLA so autodiff needs no hand-written
    VJP.

    `groups > 1` evaluates a (G·b)-sized stack of G independent val
    batches at once and returns vector metrics (see
    `grouped_eval_metrics`); the Pallas kernel is scalar-only and is
    ignored in that mode.
    """

    stateful = _is_stateful(model)

    def eval_step(params, batch: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        if stateful:
            # `params` is the full variables dict ({'params', 'batch_stats'})
            # the trainer's _eval_variables() builds; running averages only
            preds = model.apply(params, batch["image"], train=False)
        else:
            preds = model.apply({"params": params}, batch["image"])
        target = _prep_mask(batch["mask"])
        if groups > 1:
            return grouped_eval_metrics(preds, target, groups)
        if use_pallas:
            from distributedpytorch_tpu.ops.pallas_kernels import (
                eval_metrics_pallas,
            )

            return eval_metrics_pallas(preds, target)
        return {
            "loss": bce_dice_loss(preds, target),
            "dice": dice_coefficient(preds, target),
        }

    return eval_step
