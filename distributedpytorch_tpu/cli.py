"""Train UNet on images and target masks — TPU-native CLI.

Installed as the ``dpt-train`` console script (pyproject.toml); ``python
train.py`` at the repo root is the same entry point under the reference's
launch surface.

Flag-for-flag parity with the reference entry point (reference
train.py:15-26): same short/long names, same defaults, same ``-t`` method
names (singleGPU | DP | DDP | MP), plus the new ``DDP_MP`` hybrid and a few
additive flags (--synthetic, --microbatches, --profile-dir, --export-pth).

Launch parity (reference README.md:25-44):
    python3 train.py                      # single device
    python3 train.py -t DP
    torchrun --standalone --nnodes=1 --nproc_per_node=2 train.py -t DDP -b 2
    python3 train.py -t MP
The torchrun path works because dist/runtime.py maps torchrun's env contract
onto `jax.distributed.initialize` (no NCCL — XLA collectives over ICI).
"""

import argparse
import logging
import os
import sys


def get_args():
    parser = argparse.ArgumentParser(
        description="Train UNet on images and target masks"
    )
    # reference flags (train.py:15-26)
    parser.add_argument("--train-method", "-t", type=str, default="singleGPU",
                        help="Training method: singleGPU | DP | DDP | MP | DDP_MP "
                             "| SP | DDP_SP | TP | FSDP, or a mesh spec "
                             "DxMxS[@fsdp|sp] over the ('data','model',"
                             "'stage') mesh — e.g. 4x1x2 (data x pipe), "
                             "2x2x1 (data x tensor), 2x2x1@fsdp, 1x4x1@sp "
                             "(docs/DISTRIBUTED.md 'The mesh engine'; the "
                             "named methods are aliases into mesh configs)")
    parser.add_argument("--validation", "-v", dest="val", type=float, default=10.0,
                        help="Percentage of data used as validation")
    parser.add_argument("--load", "-l", type=str, default=False,
                        help="Load model from a .pth file (alias of -c, which the "
                             "reference parsed but ignored)")
    parser.add_argument("--epochs", "-e", type=int, default=10, help="Number of epochs")
    parser.add_argument("--learning-rate", "--lr", type=float, default=1e-4,
                        help="Learning rate", dest="lr")
    parser.add_argument("--batch-size", "-b", type=int, default=4, help="Batch size")
    parser.add_argument("--checkpoint", "-c", type=str, default=None,
                        help="File name of the checkpoint to load")
    parser.add_argument("--seed", "-s", type=int, default=42,
                        help="Set seed for reproducibility")
    # additive flags
    parser.add_argument("--data-dir", type=str, default="./data",
                        help="Root containing train_hq/ and train_masks/")
    parser.add_argument("--synthetic", type=int, default=0,
                        help="Use N in-memory synthetic samples instead of disk data")
    parser.add_argument("--image-size", type=int, nargs=2, default=(960, 640),
                        metavar=("W", "H"), help="Resize target (W H)")
    parser.add_argument("--microbatches", type=int, default=2,
                        help="Pipeline microbatches (MP/DDP_MP); reference hardcodes 2")
    parser.add_argument("--stages", type=int, default=2,
                        help="Pipeline stages (MP/DDP_MP); 2 = the "
                             "reference's encoder|decoder cut; bubble is "
                             "(S-1)/(M+S-1), so raise --microbatches with S")
    parser.add_argument("--pipeline-cuts", type=int, nargs="+", default=None,
                        help="Explicit stage boundaries as model-segment "
                             "indices (L encoder levels, mid, L decoder "
                             "levels+head); default: faithful 2-stage cut, "
                             "even split otherwise")
    parser.add_argument("--pipeline-schedule", type=str, default="gpipe",
                        choices=["gpipe", "1f1b"],
                        help="MP/DDP_MP schedule: gpipe (fill-drain; "
                             "activation memory grows with --microbatches) "
                             "or 1f1b (PipeDream-flush; in-flight memory "
                             "bounded by --stages, grad-equivalent)")
    parser.add_argument("--num-workers", type=int, default=4,
                        help="Host-side decode threads")
    parser.add_argument("--prefetch-batches", type=int, default=2,
                        help="Batches (or K-stacks) placed on device ahead "
                             "of compute (each pins one payload of HBM; "
                             "0 = synchronous)")
    parser.add_argument("--host-cache-mb", type=int, default=1024,
                        help="Host RAM budget (MiB) for the epoch-persistent "
                             "decoded-sample cache; epochs >= 2 skip decode "
                             "for whatever fits (0 = off)")
    parser.add_argument("--sync-checkpoint", action="store_true",
                        help="Write checkpoints synchronously instead of on "
                             "the background writer thread")
    parser.add_argument("--trace-timeline", type=str, default=None,
                        metavar="PATH",
                        help="Append per-phase step-timeline spans "
                             "(decode/stack/h2d/dispatch/readback) to this "
                             "JSONL file; summarize with bench.py, export "
                             "to Perfetto via obs/trace_hub.py (rank R of "
                             "a multi-process run writes PATH.rankR)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        metavar="PORT",
                        help="Serve Prometheus /metrics (+ /healthz) on "
                             "this port for the run (rank R binds PORT+R; "
                             "0 = ephemeral)")
    parser.add_argument("--profile-steps", type=str, default=None,
                        metavar="N:M",
                        help="Capture a jax.profiler device trace from "
                             "global step N until step M into "
                             "--profile-dir (default <log-dir>/profile)")
    parser.add_argument("--steps-per-dispatch", type=int, default=1,
                        help="Optimizer steps fused into one XLA dispatch "
                             "(amortizes runtime dispatch latency)")
    parser.add_argument("--grad-accum", type=int, default=1,
                        help="Accumulate K batches into one optimizer step "
                             "(effective batch K*b, one batch's activation "
                             "memory; exact loss via stats decomposition)")
    parser.add_argument("--remat", action="store_true",
                        help="Rematerialize activations in the backward "
                             "(~half HBM, ~1/3 more FLOPs)")
    parser.add_argument("--kernels", type=str, default="xla",
                        choices=["xla", "pallas"],
                        help="Pallas kernel-engagement policy "
                             "(ops/kernels.py): xla = no fast paths "
                             "(bit-identical reference, default); pallas "
                             "= fused loss stats, one-pass eval stats, "
                             "the DoubleConv BN+ReLU epilogue, and the "
                             "serve mask kernel — each revocable by the "
                             "Mosaic probe priors")
    parser.add_argument("--kernel-priors", type=str, default=None,
                        help="Per-chip Mosaic probe priors file "
                             "(tools/probe_kernels.py): kernels the "
                             "chip's compiler rejected disengage loudly")
    parser.add_argument("--pallas", action="store_true",
                        help="LEGACY alias for the fused loss/eval-stats "
                             "kernels only — prefer --kernels pallas")
    parser.add_argument("--dtype", type=str, default="bf16",
                        choices=["f32", "bf16", "bf16_params"],
                        help="Mixed-precision policy (ops/precision.py): "
                             "f32 = pure-float32 reference; bf16 = bf16 "
                             "conv compute with f32 params/loss (default); "
                             "bf16_params = bf16 on-device params (halved "
                             "param bytes) with f32 master weights in "
                             "optimizer state. Loss, wgrad accumulation, "
                             "and grad psums stay f32 under every policy")
    parser.add_argument("--s2d-levels", type=int, default=-1,
                        help="Shallow UNet levels executed in the "
                             "space-to-depth domain (exact numerics, ~1.9x "
                             "faster on TPU); 0 disables, -1 = auto "
                             "(2 on TPU, 0 elsewhere)")
    parser.add_argument("--wgrad-taps", action="store_true",
                        help="Weight gradients of the s2d 3x3 convs as 9 "
                             "tap matmuls instead of XLA's conv backward "
                             "(identical numerics; perf A/B lever)")
    parser.add_argument("--model", dest="model_arch", type=str, default="unet",
                        choices=["unet", "milesial"],
                        help="Model family: the reference course UNet "
                             "(7.76M params) or the original "
                             "milesial/Pytorch-UNet (31M params, BatchNorm)")
    parser.add_argument("--model-widths", type=int, nargs="+", default=None,
                        help="Encoder channel widths (default 32 64 128 256, "
                             "the reference model; e.g. 64 128 256 512 for a "
                             "4x wider ~31M-param variant)")
    parser.add_argument("--profile-dir", type=str, default=None,
                        help="Capture a jax.profiler trace here")
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="On a crash, resume from the newest epoch "
                             "checkpoint up to N times (single-process "
                             "runs; multi-process restarts belong to the "
                             "launcher)")
    parser.add_argument("--save-best", action="store_true",
                        help="Keep a separate <method>_best.ckpt at the "
                             "highest validation Dice")
    parser.add_argument("--early-stop", type=int, default=0, metavar="N",
                        help="Stop when val loss has not improved for N "
                             "consecutive epochs (0 = off)")
    parser.add_argument("--export-pth", action="store_true",
                        help="Also export final weights as a reference-format .pth")
    # resilience (utils/faults.py, docs/RELIABILITY.md)
    parser.add_argument("--nonfinite-policy", type=str, default="abort",
                        choices=["abort", "rollback", "skip"],
                        help="On a non-finite train loss: abort (raise), "
                             "rollback (reload the newest intact checkpoint"
                             ", bounded by --rollback-retries), or skip "
                             "(discard that step's update; checks the loss "
                             "synchronously per step)")
    parser.add_argument("--rollback-retries", type=int, default=2,
                        help="Rollback budget for --nonfinite-policy "
                             "rollback before aborting")
    parser.add_argument("--data-retries", type=int, default=3,
                        help="Bounded exponential-backoff retries for "
                             "transient decode / placement failures "
                             "(0 = fail fast)")
    parser.add_argument("--step-timeout", type=float, default=0.0,
                        metavar="SECS",
                        help="Dispatch watchdog: a step exceeding this "
                             "dumps the step-timeline spans and "
                             "checkpoints-and-stops (0 = off)")
    parser.add_argument("--keep-checkpoints", type=int, default=2,
                        help="Retain the newest N checkpoint files per "
                             "path; restore hash-verifies and falls back "
                             "to the newest intact one")
    # default=None, not []: argparse appends into the default object
    # itself, so a shared [] would leak armed faults across repeated
    # get_args() calls in one process
    parser.add_argument("--inject-fault", action="append", default=None,
                        metavar="SITE[@RANK]:EPOCH:STEP[:COUNT]",
                        help="Arm a deterministic fault (repeatable; "
                             "sites: decode, placement, nan_loss, "
                             "ckpt_write, sigterm, rank_kill, rank_hang; "
                             "'*' wildcards, '@RANK' pins one process) — "
                             "for recovery drills and tests")
    # elastic runtime (dist/elastic.py appends these to every worker)
    parser.add_argument("--checkpoint-dir", type=str, default="./checkpoints",
                        help="Where epoch checkpoints live (the elastic "
                             "supervisor resumes from here)")
    parser.add_argument("--heartbeat-dir", type=str, default=None,
                        help="Write a per-rank heartbeat file here (armed "
                             "by the elastic supervisor; off when unset)")
    parser.add_argument("--heartbeat-interval", type=float, default=0.5,
                        help="Heartbeat write cadence in seconds")
    return parser.parse_args()


def resolve_checkpoint_arg(args):
    """The -c/-l aliasing: -c wins, then -l (which the reference parses but
    ignores — here it actually loads, reference train.py:19 vs :23)."""
    return args.checkpoint or args.load or None


def parse_profile_steps(text):
    """``--profile-steps N:M`` → (N, M) with 0 <= N < M."""
    if not text:
        return None
    try:
        lo, _, hi = str(text).partition(":")
        lo, hi = int(lo), int(hi)
    except ValueError:
        raise ValueError(
            f"--profile-steps expects N:M (global steps), got {text!r}"
        ) from None
    if lo < 0 or hi <= lo:
        raise ValueError(
            f"--profile-steps needs 0 <= N < M, got {text!r}"
        )
    return (lo, hi)


def _channel_shaped(exc: BaseException) -> bool:
    """Does this exception look like a dead/flapping runtime channel —
    i.e. a PEER failure, not this rank's own bug? One definition with
    the retry taxonomy (utils/faults.is_transient): the OSError family
    plus grpc/socket-marked RuntimeErrors, which is exactly how a gloo
    peer's death presents on every survivor."""
    from distributedpytorch_tpu.utils.faults import is_transient

    return is_transient(exc)


def _enable_compilation_cache():
    """Persistent XLA compilation cache: first-run UNet compiles cost
    20-40 s on TPU; subsequent launches reload them from disk. Best-effort
    (older jax versions or unsupported backends simply skip it)."""
    try:
        import jax

        cache_dir = os.environ.get(
            "DPT_COMPILATION_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache", "dpt_xla_cache"),
        )
        if cache_dir:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:  # pragma: no cover
        pass


def main():
    args = get_args()
    _enable_compilation_cache()

    # Multi-process init must precede any other jax call (reference
    # train.py:58's init_process_group slot).
    from distributedpytorch_tpu.dist import initialize_from_env, shutdown

    runtime = initialize_from_env()

    from distributedpytorch_tpu.config import TrainConfig
    from distributedpytorch_tpu.train import Trainer
    from distributedpytorch_tpu.utils.seeding import set_seed

    set_seed(args.seed)

    config = TrainConfig(
        train_method=args.train_method,
        epochs=args.epochs,
        learning_rate=args.lr,
        batch_size=args.batch_size,
        val_percent=args.val,
        seed=args.seed,
        data_dir=args.data_dir,
        image_size=tuple(args.image_size),
        num_microbatches=args.microbatches,
        num_stages=args.stages,
        pipeline_cuts=tuple(args.pipeline_cuts) if args.pipeline_cuts else None,
        pipeline_schedule=args.pipeline_schedule,
        num_workers=args.num_workers,
        prefetch_batches=args.prefetch_batches,
        host_cache_mb=args.host_cache_mb,
        async_checkpoint=not args.sync_checkpoint,
        timeline_path=args.trace_timeline,
        steps_per_dispatch=args.steps_per_dispatch,
        grad_accum=args.grad_accum,
        remat=args.remat,
        use_pallas=args.pallas,
        kernels=args.kernels,
        kernel_priors=args.kernel_priors,
        model_arch=args.model_arch,
        model_widths=tuple(args.model_widths) if args.model_widths else None,
        dtype=args.dtype,
        s2d_levels=args.s2d_levels,
        wgrad_taps=args.wgrad_taps,
        checkpoint_name=resolve_checkpoint_arg(args),
        synthetic_samples=args.synthetic,
        profile_dir=args.profile_dir,
        save_best=args.save_best,
        early_stop_patience=args.early_stop,
        nonfinite_policy=args.nonfinite_policy,
        rollback_retries=args.rollback_retries,
        data_retries=args.data_retries,
        step_timeout_s=args.step_timeout,
        keep_checkpoints=args.keep_checkpoints,
        inject_faults=tuple(args.inject_fault or ()),
        checkpoint_dir=args.checkpoint_dir,
        heartbeat_dir=args.heartbeat_dir,
        heartbeat_interval_s=args.heartbeat_interval,
        metrics_port=args.metrics_port,
        profile_steps=parse_profile_steps(args.profile_steps),
    )

    # logfile parity: ./logs/{method}.log, append, message-only (reference
    # train.py:37-38) — plus stderr mirroring, rank 0 only.
    os.makedirs(config.log_dir, exist_ok=True)
    handlers = [
        logging.FileHandler(
            os.path.join(config.log_dir, f"{config.method_tag}.log"), mode="a"
        )
    ]
    if runtime.is_main:
        handlers.append(logging.StreamHandler(sys.stderr))
    logging.basicConfig(level=logging.INFO, format="%(message)s", handlers=handlers)
    logging.info("UNet for Carvana Image Masking (Segmentation)")

    try:
        try:
            if args.max_restarts > 0:
                from distributedpytorch_tpu.train import fit_with_restarts

                result, trainer = fit_with_restarts(
                    config, max_restarts=args.max_restarts, return_trainer=True
                )
            else:
                trainer = Trainer(config)
                result = trainer.train()
        except Exception as exc:  # noqa: BLE001 — classified, then re-raised
            if runtime.num_processes > 1 and _channel_shaped(exc):
                # A dead/hung gloo peer surfaces on EVERY survivor as a
                # wall of channel-shaped tracebacks that say nothing
                # about which rank actually failed. Print ONE line and
                # exit with the peer-failure code; the elastic
                # supervisor's health classifier owns the real
                # attribution (`rank R: <dead|hung|desynced> at
                # epoch:step`, dist/health.py) and treats this exit as
                # a casualty, not a cause.
                logging.error(
                    "rank %d: aborting on distributed peer failure "
                    "(%s: %.200s) — see the supervisor's per-rank summary",
                    runtime.process_id, type(exc).__name__, exc,
                )
                # os._exit, NOT sys.exit: SystemExit would unwind into
                # the finally's shutdown(), whose coordination barrier
                # blocks on the very peer that just died (the hazard
                # tests/ddp_worker.py documents) — the survivor would
                # hang until the supervisor SIGKILLs it and the
                # PEER_FAILURE_EXIT attribution would be lost.
                logging.shutdown()
                sys.stdout.flush()
                sys.stderr.flush()
                os._exit(13)  # dist/elastic.PEER_FAILURE_EXIT
            raise
        if args.export_pth and runtime.is_main:
            pth = os.path.join(config.checkpoint_dir, f"{config.method_tag}.pth")
            if config.model_arch == "milesial":
                from distributedpytorch_tpu.checkpoint import export_milesial_pth

                export_milesial_pth(
                    trainer.state.params, trainer.state.model_state, pth
                )
            else:
                from distributedpytorch_tpu.checkpoint import export_reference_pth

                export_reference_pth(trainer.state.params, pth)
        logging.info("Done: %s", result)
    finally:
        shutdown()


if __name__ == "__main__":
    main()
