"""The original milesial/Pytorch-UNet architecture, TPU-native.

The reference documents this model as the ancestor of its own UNet
(reference model/modelsummary.txt:150-247: DoubleConv/Down/Up/OutConv
blocks, BatchNorm after every conv, 31,037,698 trainable parameters at
n_classes=2 with transposed-conv upsampling). This is the second model
family the framework ships; parameter-count golden in tests/test_model.py.

Differences from `models/unet.py`'s reference-course model: twice the
widths (64→1024 vs 32→512), BatchNorm (bias-free convs), no explicit mid
block (the deepest Down plays that role), and an optional bilinear
upsampling mode (halves the deepest width, parameter-free Up).

TPU notes:
  * NHWC, bfloat16 convs — but BatchNorm runs in float32 (variance in
    bf16 is numerically unsafe) and casts back.
  * BatchNorm is STATEFUL: `init` returns a `batch_stats` collection
    alongside `params`, and the train step must apply with
    ``mutable=["batch_stats"]`` (train/steps.py `make_train_step` does
    this automatically — `TrainState.model_state` carries the running
    stats). Under a GSPMD data-parallel mesh the batch axis is sharded,
    so the batch statistics XLA computes are GLOBAL-batch statistics:
    data-parallel training gets SyncBN semantics by construction, unlike
    torch where `SyncBatchNorm` is a separate opt-in wrapper.
  * For ``n_classes=1`` (this repo's binary-segmentation task) the output
    is sigmoid probabilities in float32, matching `models/unet.py`'s
    contract; for 2+ classes raw logits are returned (milesial trains
    those with cross-entropy).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributedpytorch_tpu.models.unet import center_crop

MILESIAL_WIDTHS = (64, 128, 256, 512, 1024)


class DoubleConv(nn.Module):
    """[Conv3×3(no bias) → BatchNorm → ReLU] × 2
    (reference model/modelsummary.txt:155-160)."""

    features: int
    mid_features: int = 0  # 0 = features (bilinear Up passes in//2)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        mid = self.mid_features or self.features
        for i, feats in enumerate((mid, self.features)):
            x = nn.Conv(
                feats, (3, 3), padding=1, use_bias=False, dtype=self.dtype,
                name=f"conv{i + 1}",
            )(x)
            # float32 statistics; torch defaults are eps=1e-5, momentum=0.1
            # (flax momentum = 1 − torch momentum)
            x = nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=1e-5,
                dtype=jnp.float32, name=f"bn{i + 1}",
            )(x.astype(jnp.float32))
            x = nn.relu(x).astype(self.dtype)
        return x


class Down(nn.Module):
    """MaxPool(2) → DoubleConv (reference modelsummary.txt:161-169)."""

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        return DoubleConv(self.features, dtype=self.dtype, name="conv")(x, train)


class Up(nn.Module):
    """Upsample → concat skip → DoubleConv (reference modelsummary.txt:193-201).

    ``bilinear=False`` (the documented 31M config): ConvTranspose(k=2,s=2)
    halving the channels. ``bilinear=True``: parameter-free bilinear resize,
    DoubleConv with mid = in//2 (milesial's memory-saving mode).
    """

    features: int
    bilinear: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(
        self, x: jax.Array, skip: jax.Array, train: bool = False
    ) -> jax.Array:
        if self.bilinear:
            b, h, w, c = x.shape
            x = jax.image.resize(x, (b, 2 * h, 2 * w, c), method="bilinear")
            # milesial: DoubleConv(in_channels, out, mid=in_channels // 2)
            # where in_channels is the CONCATENATED width (skip + upsampled)
            mid = (x.shape[-1] + skip.shape[-1]) // 2
        else:
            x = nn.ConvTranspose(
                x.shape[-1] // 2, (2, 2), strides=(2, 2), dtype=self.dtype,
                name="up",
            )(x)
            mid = 0
        skip = center_crop(skip, (x.shape[1], x.shape[2]))
        x = jnp.concatenate([skip, x], axis=-1)
        return DoubleConv(
            self.features, mid_features=mid, dtype=self.dtype, name="conv"
        )(x, train)


class MilesialUNet(nn.Module):
    """inc → Down×4 → Up×4 → OutConv (reference modelsummary.txt:150-247)."""

    n_classes: int = 1
    bilinear: bool = False
    widths: Sequence[int] = MILESIAL_WIDTHS
    dtype: Any = jnp.bfloat16

    # train/steps.py keys off this to thread the batch_stats collection
    is_stateful = True

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        w = tuple(self.widths)
        assert len(w) >= 2, "milesial needs at least inc + one Down level"
        factor = 2 if self.bilinear else 1
        x = DoubleConv(w[0], dtype=self.dtype, name="inc")(x, train)
        skips = [x]
        for i, feats in enumerate(w[1:-1]):
            x = Down(feats, dtype=self.dtype, name=f"down{i + 1}")(x, train)
            skips.append(x)
        x = Down(w[-1] // factor, dtype=self.dtype, name=f"down{len(w) - 1}")(
            x, train
        )
        for i, (feats, skip) in enumerate(zip(reversed(w[:-1]), reversed(skips))):
            x = Up(
                feats // (factor if i < len(w) - 2 else 1),
                bilinear=self.bilinear,
                dtype=self.dtype,
                name=f"up{i + 1}",
            )(x, skip, train)
        x = nn.Conv(self.n_classes, (1, 1), dtype=self.dtype, name="outc")(x)
        if self.n_classes == 1:
            return jax.nn.sigmoid(x.astype(jnp.float32))
        return x.astype(jnp.float32)


def init_milesial(
    model: MilesialUNet, rng: jax.Array, input_hw: Tuple[int, int] = (64, 96)
):
    """Initialize; returns ``(params, batch_stats)``."""
    dummy = jnp.zeros((1, input_hw[0], input_hw[1], 3), jnp.float32)
    variables = model.init(rng, dummy, train=False)
    return variables["params"], variables["batch_stats"]
