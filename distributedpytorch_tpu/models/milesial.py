"""The original milesial/Pytorch-UNet architecture, TPU-native.

The reference documents this model as the ancestor of its own UNet
(reference model/modelsummary.txt:150-247: DoubleConv/Down/Up/OutConv
blocks, BatchNorm after every conv, 31,037,698 trainable parameters at
n_classes=2 with transposed-conv upsampling). This is the second model
family the framework ships; parameter-count golden in tests/test_model.py.

Differences from `models/unet.py`'s reference-course model: twice the
widths (64→1024 vs 32→512), BatchNorm (bias-free convs), no explicit mid
block (the deepest Down plays that role), and an optional bilinear
upsampling mode (halves the deepest width, parameter-free Up).

TPU notes:
  * NHWC, bfloat16 convs — but BatchNorm runs in float32 (variance in
    bf16 is numerically unsafe) and casts back.
  * BatchNorm is STATEFUL: `init` returns a `batch_stats` collection
    alongside `params`, and the train step must apply with
    ``mutable=["batch_stats"]`` (train/steps.py `make_train_step` does
    this automatically — `TrainState.model_state` carries the running
    stats). Under a GSPMD data-parallel mesh the batch axis is sharded,
    so the batch statistics XLA computes are GLOBAL-batch statistics:
    data-parallel training gets SyncBN semantics by construction, unlike
    torch where `SyncBatchNorm` is a separate opt-in wrapper.
  * For ``n_classes=1`` (this repo's binary-segmentation task) the output
    is sigmoid probabilities in float32, matching `models/unet.py`'s
    contract; for 2+ classes raw logits are returned (milesial trains
    those with cross-entropy).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributedpytorch_tpu.models.unet import (
    _S2DConv,
    _TapsPixelConv,
    center_crop,
)
from distributedpytorch_tpu.ops import s2d as s2d_ops

MILESIAL_WIDTHS = (64, 128, 256, 512, 1024)


class _S2DBatchNorm(nn.Module):
    """BatchNorm evaluated on a g-major space-to-depth tensor, EXACTLY
    equal to pixel-domain BatchNorm (up to reduction order): channel c of
    the underlying (B, H, W, C) image lives at s2d channels {g·C+c}, so
    per-logical-channel statistics reduce over (batch, h, w, g) — the
    same value set pixel BN reduces over (batch, H, W). Parameters and
    running statistics are (C,)-shaped with nn.BatchNorm's names, so
    checkpoints and `.pth` interop are identical across execution modes
    (the s2d contract, ops/s2d.py).

    Matches the pixel path's nn.BatchNorm config (milesial: momentum 0.9
    flax-convention, eps 1e-5, float32 statistics).
    """

    features: int  # logical channels C (input carries 4C)
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        C = self.features
        scale = self.param("scale", nn.initializers.ones_init(), (C,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (C,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((C,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((C,), jnp.float32)
        )
        b, h, w, c4 = x.shape
        assert c4 == 4 * C, (c4, C)
        xg = x.astype(jnp.float32).reshape(b, h, w, 4, C)
        if train:
            mean = jnp.mean(xg, axis=(0, 1, 2, 3))
            var = jnp.var(xg, axis=(0, 1, 2, 3))
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1.0 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1.0 - self.momentum) * var
                )
        else:
            mean, var = ra_mean.value, ra_var.value
        y = (xg - mean) * jax.lax.rsqrt(var + self.epsilon) * scale + bias
        return y.reshape(b, h, w, c4)


class DoubleConvS2D(nn.Module):
    """`DoubleConv` in the space-to-depth domain: bias-free structured
    dense convs (kernels assembled from the original (3,3,Cin,Cout)
    params) + exact s2d BatchNorm. Param tree identical to `DoubleConv`
    (conv1/bn1/conv2/bn2, same shapes)."""

    features: int
    in_features: int
    in_segments: Optional[Tuple[int, ...]] = None
    dtype: Any = jnp.bfloat16
    wgrad_taps: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        x = _S2DConv(
            self.features, self.in_features, "conv3x3", dtype=self.dtype,
            in_segments=self.in_segments, wgrad_taps=self.wgrad_taps,
            use_bias=False, name="conv1",
        )(x)
        x = _S2DBatchNorm(self.features, name="bn1")(x, train)
        x = nn.relu(x).astype(self.dtype)
        x = _S2DConv(
            self.features, self.features, "conv3x3", dtype=self.dtype,
            wgrad_taps=self.wgrad_taps, use_bias=False, name="conv2",
        )(x)
        x = _S2DBatchNorm(self.features, name="bn2")(x, train)
        return nn.relu(x).astype(self.dtype)


class _DownS2D(nn.Module):
    """`Down` where the s2d execution domain touches either side of the
    pool: the 2×2 maxpool of an s2d input is a max over the s2d group
    (ops/s2d.py `group_max`), and the conv runs in whichever domain its
    level belongs to. Param tree identical to `Down`."""

    features: int
    in_features: int
    prev_s2d: bool  # input arrives in s2d form
    this_s2d: bool  # this level's DoubleConv runs in the s2d domain
    dtype: Any = jnp.bfloat16
    wgrad_taps: bool = False
    epilogue: bool = False  # pixel-domain DoubleConv only (the boundary)

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        x = (
            s2d_ops.group_max(x)
            if self.prev_s2d
            else nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        )
        if self.this_s2d:
            x = s2d_ops.space_to_depth(x)
            return DoubleConvS2D(
                self.features, in_features=self.in_features,
                dtype=self.dtype, wgrad_taps=self.wgrad_taps, name="conv",
            )(x, train)
        return DoubleConv(
            self.features, dtype=self.dtype, wgrad_taps=self.wgrad_taps,
            epilogue=self.epilogue, name="conv",
        )(x, train)


class _UpS2D(nn.Module):
    """`Up` (transposed-conv mode) in the s2d domain: the k=2 s=2
    ConvTranspose becomes a 1×1 conv from the pixel-space input
    (ops/s2d.py `upconv_kernel`), the skip arrives already in s2d form,
    and the concat is a kernel-layout concern (`in_segments`). Param tree
    identical to `Up(bilinear=False)`."""

    features: int
    skip_features: int
    prev_s2d: bool  # x arrives in s2d form (previous Up ran s2d)
    dtype: Any = jnp.bfloat16
    wgrad_taps: bool = False

    @nn.compact
    def __call__(
        self, x: jax.Array, skip: jax.Array, train: bool = False
    ) -> jax.Array:
        if self.prev_s2d:
            x = s2d_ops.depth_to_space(x)
        up_feats = x.shape[-1] // 2
        up = _S2DConv(
            up_feats, x.shape[-1], "upconv", dtype=self.dtype, name="up"
        )(x)
        assert skip.shape[:3] == up.shape[:3], (
            "s2d Up expects the identity center-crop (even input sizes): "
            f"skip {skip.shape} vs upconv {up.shape}"
        )
        x = jnp.concatenate([skip, up], axis=-1)
        return DoubleConvS2D(
            self.features,
            in_features=self.skip_features + up_feats,
            in_segments=(self.skip_features, up_feats),
            dtype=self.dtype,
            wgrad_taps=self.wgrad_taps,
            name="conv",
        )(x, train)


class _FusedEpilogueBatchNorm(nn.Module):
    """``nn.BatchNorm`` + ReLU with the normalize+activation tail in ONE
    fused VMEM pass (ops/kernels.fused_bn_act — the ``--kernels pallas``
    conv-epilogue engagement site). Parameter and ``batch_stats`` trees
    are EXACTLY ``nn.BatchNorm``'s (scale/bias params, mean/var stats —
    same names, shapes, inits), so checkpoints are interchangeable with
    the XLA path. The batch statistics themselves (mean/var reductions +
    running-average updates, mirroring flax's fast-variance formula) stay
    XLA: they are reductions the compiler already fuses, and keeping them
    outside lets autodiff chain d(mean)/d(var) → x through the kernel's
    hand-written VJP. Matches the XLA twin to float-rounding tolerance
    (the folded affine associates differently — tests/test_kernels.py)."""

    features: int
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        from distributedpytorch_tpu.ops.kernels import fused_bn_act

        C = self.features
        scale = self.param(
            "scale", nn.initializers.ones_init(), (C,), jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (C,), jnp.float32
        )
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((C,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((C,), jnp.float32)
        )
        xf = x.astype(jnp.float32)
        if train:
            # flax _compute_stats fast-variance: E[x²] − E[x]², clipped
            mean = jnp.mean(xf, axis=(0, 1, 2))
            mean2 = jnp.mean(jnp.square(xf), axis=(0, 1, 2))
            var = jnp.maximum(0.0, mean2 - jnp.square(mean))
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1.0 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1.0 - self.momentum) * var
                )
        else:
            mean, var = ra_mean.value, ra_var.value
        return fused_bn_act(xf, mean, var, scale, bias, epsilon=self.epsilon)


class DoubleConv(nn.Module):
    """[Conv3×3(no bias) → BatchNorm → ReLU] × 2
    (reference model/modelsummary.txt:155-160).

    ``epilogue=True`` fuses each BN-normalize + ReLU tail into one VMEM
    pass (``_FusedEpilogueBatchNorm``) while XLA keeps the conv itself —
    the ``--kernels pallas`` conv-epilogue engagement; identical param
    tree either way."""

    features: int
    mid_features: int = 0  # 0 = features (bilinear Up passes in//2)
    dtype: Any = jnp.bfloat16
    wgrad_taps: bool = False
    epilogue: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        mid = self.mid_features or self.features
        for i, feats in enumerate((mid, self.features)):
            if self.wgrad_taps:
                x = _TapsPixelConv(
                    feats, dtype=self.dtype, use_bias=False,
                    name=f"conv{i + 1}",
                )(x)
            else:
                x = nn.Conv(
                    feats, (3, 3), padding=1, use_bias=False, dtype=self.dtype,
                    name=f"conv{i + 1}",
                )(x)
            if self.epilogue:
                x = _FusedEpilogueBatchNorm(
                    feats, name=f"bn{i + 1}"
                )(x, train).astype(self.dtype)
                continue
            # float32 statistics; torch defaults are eps=1e-5, momentum=0.1
            # (flax momentum = 1 − torch momentum)
            x = nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=1e-5,
                dtype=jnp.float32, name=f"bn{i + 1}",
            )(x.astype(jnp.float32))
            x = nn.relu(x).astype(self.dtype)
        return x


class Down(nn.Module):
    """MaxPool(2) → DoubleConv (reference modelsummary.txt:161-169)."""

    features: int
    dtype: Any = jnp.bfloat16
    wgrad_taps: bool = False
    epilogue: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        return DoubleConv(
            self.features, dtype=self.dtype, wgrad_taps=self.wgrad_taps,
            epilogue=self.epilogue, name="conv",
        )(x, train)


class Up(nn.Module):
    """Upsample → concat skip → DoubleConv (reference modelsummary.txt:193-201).

    ``bilinear=False`` (the documented 31M config): ConvTranspose(k=2,s=2)
    halving the channels. ``bilinear=True``: parameter-free bilinear resize,
    DoubleConv with mid = in//2 (milesial's memory-saving mode).
    """

    features: int
    bilinear: bool = False
    dtype: Any = jnp.bfloat16
    wgrad_taps: bool = False
    epilogue: bool = False

    @nn.compact
    def __call__(
        self, x: jax.Array, skip: jax.Array, train: bool = False
    ) -> jax.Array:
        if self.bilinear:
            b, h, w, c = x.shape
            x = jax.image.resize(x, (b, 2 * h, 2 * w, c), method="bilinear")
            # milesial: DoubleConv(in_channels, out, mid=in_channels // 2)
            # where in_channels is the CONCATENATED width (skip + upsampled)
            mid = (x.shape[-1] + skip.shape[-1]) // 2
        else:
            x = nn.ConvTranspose(
                x.shape[-1] // 2, (2, 2), strides=(2, 2), dtype=self.dtype,
                name="up",
            )(x)
            mid = 0
        skip = center_crop(skip, (x.shape[1], x.shape[2]))
        x = jnp.concatenate([skip, x], axis=-1)
        return DoubleConv(
            self.features, mid_features=mid, dtype=self.dtype,
            wgrad_taps=self.wgrad_taps, epilogue=self.epilogue, name="conv",
        )(x, train)


class MilesialUNet(nn.Module):
    """inc → Down×4 → Up×4 → OutConv (reference modelsummary.txt:150-247).

    ``s2d_levels`` executes the shallowest levels in the space-to-depth
    domain (ops/s2d.py), exactly like models/unet.py's flagship model —
    level 0 is the full-resolution `inc` stem (64 channels at 640×960:
    the same MXU-starving shape the course model's s2d rewrite attacks),
    level i is `down_i`. BatchNorm statistics stay exact via
    `_S2DBatchNorm` (reduced over the s2d group axis as well as
    batch × space). -1 = auto (2 on TPU, 0 elsewhere); requires
    ``bilinear=False`` (the documented 31M config) and input sizes
    divisible by 2**levels.
    """

    n_classes: int = 1
    bilinear: bool = False
    widths: Sequence[int] = MILESIAL_WIDTHS
    dtype: Any = jnp.bfloat16
    s2d_levels: int = -1
    wgrad_taps: bool = False
    # Fuse every pixel-domain DoubleConv's BN-normalize + ReLU into one
    # VMEM pass (ops/kernels.fused_bn_act, --kernels pallas). Identical
    # param/batch_stats trees; s2d-domain levels keep _S2DBatchNorm.
    # Engagement is the model factory's call (models/__init__.py via
    # ops/kernels.conv_epilogue_engaged — device-local forwards only).
    conv_epilogue: bool = False

    # train/steps.py and parallel/pipeline.py key off this to thread the
    # batch_stats collection
    is_stateful = True

    def _s2d_levels(self) -> int:
        auto = self.s2d_levels < 0
        lv = (2 if jax.default_backend() == "tpu" else 0) if auto else self.s2d_levels
        lv = max(0, min(lv, len(self.widths) - 2))
        if lv > 0 and self.bilinear:
            if auto:  # auto never breaks a previously-valid config
                return 0
            raise ValueError(
                "s2d execution supports the transposed-conv decoder only "
                "(bilinear=False) — pass s2d_levels=0 with bilinear"
            )
        return lv

    # -- pipeline segments (parallel/pipeline.py) ---------------------------
    # The family's linear block order: inc, L Down levels, L Up levels with
    # the 1×1 outc head folded into the last — 2L+1 segments, the same
    # carry convention as models/unet.py (encoder segments push skips,
    # decoder segments pop; inc's output IS its own skip, milesial-style).
    @property
    def num_segments(self) -> int:
        return 2 * (len(self.widths) - 1) + 1

    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        lv = self._s2d_levels()
        if lv > 0:
            div = 2 ** (len(self.widths) - 1)
            h_, w_ = x.shape[1], x.shape[2]
            if h_ % div or w_ % div:
                if self.s2d_levels < 0:
                    # auto mode degrades to the (center-crop-tolerant)
                    # pixel path rather than rejecting a size the model
                    # handled before s2d existed
                    lv = 0
                else:
                    raise ValueError(
                        f"input {h_}×{w_} is not divisible by {div} "
                        f"(2**levels), which the space-to-depth execution "
                        f"mode requires — resize the input or pass "
                        f"s2d_levels=0 (CLI: --s2d-levels 0)"
                    )
        x, _skips = self._apply_segments(x, (), 0, self.num_segments, train, lv)
        return x

    def apply_segment(
        self, x: jax.Array, skips: Tuple[jax.Array, ...], seg: int,
        train: bool = False,
    ) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
        """Run segment ``seg`` (static int) of the linear block order —
        the stateful `(params, batch_stats) → (y, batch_stats')` path the
        pipeline schedules thread: apply with ``mutable=['batch_stats']``
        and ``train=True`` to get this segment's BatchNorm updates
        (batch statistics are per-microbatch, GPipe-style; the schedule
        psums the running-stat deltas across the stage axis).

        The s2d execution domain of every segment is a static function of
        the CONFIGURED level count, so stages can start at any segment
        without threading domain state; a ragged input therefore fails
        fast here (the full forward's auto-degrade would silently pick a
        different domain per stage)."""
        lv = self._s2d_levels()
        if seg == 0 and lv > 0:
            div = 2 ** (len(self.widths) - 1)
            h_, w_ = x.shape[1], x.shape[2]
            if h_ % div or w_ % div:
                raise ValueError(
                    f"input {h_}×{w_} is not divisible by {div} "
                    f"(2**levels), which the space-to-depth execution mode "
                    f"requires under the pipeline schedule — resize the "
                    f"input or pass s2d_levels=0 (CLI: --s2d-levels 0)"
                )
        return self._apply_segments(x, tuple(skips), seg, seg + 1, train, lv)

    @nn.compact
    def _apply_segments(
        self,
        x: jax.Array,
        skips: Tuple[jax.Array, ...],
        first: int,
        last: int,
        train: bool,
        lv: int,
    ) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
        """Segments [first, last) of the linear block order. Module names
        ("inc", "down{i}", "up{i}", "outc") are explicit, so any segment
        subset builds the same parameter tree entries as the full forward
        — what lets `apply_segment` run one segment against the full
        variables dict."""
        w = tuple(self.widths)
        assert len(w) >= 2, "milesial needs at least inc + one Down level"
        factor = 2 if self.bilinear else 1
        L = len(w) - 1  # downs; also the number of Ups
        skips = tuple(skips)
        for seg in range(first, last):
            if seg == 0:  # inc stem; its output is also the first skip
                if lv > 0:
                    xs = s2d_ops.space_to_depth(x)
                    x = DoubleConvS2D(
                        w[0], in_features=x.shape[-1], dtype=self.dtype,
                        wgrad_taps=self.wgrad_taps, name="inc",
                    )(xs, train)
                else:
                    x = DoubleConv(
                        w[0], dtype=self.dtype, wgrad_taps=self.wgrad_taps,
                        epilogue=self.conv_epilogue, name="inc",
                    )(x, train)
                skips = skips + (x,)
            elif seg <= L:  # Down level `seg`
                level = seg
                feats = w[level] // (factor if level == L else 1)
                if level < lv or (level == lv and lv > 0):
                    # s2d level, or the boundary Down whose pool consumes
                    # an s2d input (group_max) but convs in the pixel
                    # domain
                    x = _DownS2D(
                        feats, in_features=w[level - 1],
                        prev_s2d=level - 1 < lv, this_s2d=level < lv,
                        dtype=self.dtype, wgrad_taps=self.wgrad_taps,
                        epilogue=self.conv_epilogue, name=f"down{level}",
                    )(x, train)
                else:
                    x = Down(
                        feats, dtype=self.dtype, wgrad_taps=self.wgrad_taps,
                        epilogue=self.conv_epilogue, name=f"down{level}",
                    )(x, train)
                if level < L:  # the deepest Down is the bottleneck, no skip
                    skips = skips + (x,)
            else:  # Up level; the last one carries outc + activation
                i = seg - L - 1  # 0-based Up index
                feats = w[L - 1 - i]
                out_feats = feats // (factor if i < L - 1 else 1)
                skip = skips[-1]
                skips = skips[:-1]
                if i >= L - lv:
                    # shallowest lv Ups: skip is s2d-form, output stays s2d
                    x = _UpS2D(
                        out_feats,
                        skip_features=feats,
                        prev_s2d=i - 1 >= L - lv,
                        dtype=self.dtype,
                        wgrad_taps=self.wgrad_taps,
                        name=f"up{i + 1}",
                    )(x, skip, train)
                else:
                    x = Up(
                        out_feats,
                        bilinear=self.bilinear,
                        dtype=self.dtype,
                        wgrad_taps=self.wgrad_taps,
                        epilogue=self.conv_epilogue,
                        name=f"up{i + 1}",
                    )(x, skip, train)
                if seg == 2 * L:
                    if lv > 0:
                        x = _S2DConv(
                            self.n_classes, w[0], "head", dtype=self.dtype,
                            name="outc",
                        )(x)
                        x = s2d_ops.depth_to_space(x)
                    else:
                        x = nn.Conv(
                            self.n_classes, (1, 1), dtype=self.dtype,
                            name="outc",
                        )(x)
                    if self.n_classes == 1:
                        x = jax.nn.sigmoid(x.astype(jnp.float32))
                    else:
                        x = x.astype(jnp.float32)
        return x, skips


def init_milesial(
    model: MilesialUNet, rng: jax.Array, input_hw: Tuple[int, int] = (64, 96)
):
    """Initialize; returns ``(params, batch_stats)``."""
    dummy = jnp.zeros((1, input_hw[0], input_hw[1], 3), jnp.float32)
    variables = model.init(rng, dummy, train=False)
    return variables["params"], variables["batch_stats"]
