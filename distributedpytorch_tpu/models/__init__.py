from distributedpytorch_tpu.models.unet import UNet, ConvBlock, Encoder, Decoder  # noqa: F401
from distributedpytorch_tpu.models.milesial import MilesialUNet  # noqa: F401


def create_model(config):
    """Model factory: TrainConfig.model_arch → (model, init_fn).

    ``init_fn(rng, input_hw) -> (params, model_state_or_None)`` — stateful
    models (milesial's BatchNorm) return their non-trainable collections as
    the second element. The model's compute dtype comes from the resolved
    precision policy (config.precision — ops/precision.py), so ``--dtype``
    and the legacy ``compute_dtype`` override resolve in exactly one place;
    the kernel policy's conv-epilogue engagement resolves through
    ``ops.kernels.conv_epilogue_engaged`` the same way (``--kernels``,
    Mosaic probe priors, and the device-local-forward gate in one place).
    """
    from distributedpytorch_tpu.ops.precision import get_policy

    compute_dtype = get_policy(config).compute_dtype
    arch = getattr(config, "model_arch", "unet")
    if arch == "unet":
        from distributedpytorch_tpu.models.unet import create_unet, init_unet_params

        model = create_unet(config, dtype=compute_dtype)

        def init_fn(rng, input_hw):
            return init_unet_params(model, rng, input_hw=input_hw), None

        return model, init_fn
    if arch == "milesial":
        from distributedpytorch_tpu.models.milesial import (
            MILESIAL_WIDTHS,
            init_milesial,
        )

        from distributedpytorch_tpu.ops.kernels import conv_epilogue_engaged

        widths = tuple(config.model_widths) if config.model_widths else MILESIAL_WIDTHS
        model = MilesialUNet(
            widths=widths,
            dtype=compute_dtype,
            s2d_levels=getattr(config, "s2d_levels", -1),
            wgrad_taps=getattr(config, "wgrad_taps", False),
            conv_epilogue=conv_epilogue_engaged(config),
        )

        def init_fn(rng, input_hw):
            return init_milesial(model, rng, input_hw=input_hw)

        return model, init_fn
    raise ValueError(f"unknown model_arch {arch!r} (expected 'unet' or 'milesial')")
