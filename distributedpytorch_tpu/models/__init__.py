from distributedpytorch_tpu.models.unet import UNet, ConvBlock, Encoder, Decoder  # noqa: F401
