"""UNet for binary segmentation, TPU-native (flax.linen, NHWC).

Capability parity with the reference model (reference model/unet_parts.py:6-77,
model/unet_model.py:4-62): a 4-down/4-up UNet with channel widths
3→32→64→128→256, a 256→512 mid block, symmetric decoder with skip
concatenation, a 1×1 segmentation head, and a sigmoid output. Parameter-count
golden: 7,760,097 trainable parameters (reference model/modelsummary.txt:63).

TPU-first divergences from the reference (deliberate, not bugs):
  * NHWC layout throughout — XLA:TPU tiles the channel dimension onto the
    (8,128)/(16,128) vector lanes; NCHW would force relayouts around every
    conv. The data pipeline emits NHWC; a checkpoint shim handles NCHW
    interop (see checkpoint.py).
  * Convolutions are `flax.linen.Conv` → `lax.conv_general_dilated`; maxpool
    is `lax.reduce_window`; the 2×2-stride-2 up-convolution is
    `flax.linen.ConvTranspose` → `lax.conv_transpose`. All lower to MXU/VPU
    ops — no Python-level loops.
  * Compute dtype is configurable (default bfloat16 for the MXU); parameters
    are float32.
  * The shallow levels execute in the 2×2 space-to-depth domain by default
    (``s2d_levels=2``, ops/s2d.py): the full-resolution C=32/64 convs run at
    ~2.5% of MXU peak in pixel form but ~19% as structured 4C-channel convs
    at half resolution — an exactly-equivalent rewrite (same parameters,
    same function; tests/test_s2d.py) worth ~1.9× step time at the
    reference config.
  * The center-crop of skip tensors (reference unet_parts.py:58-73 uses
    torchvision CenterCrop) is a static slice; with 'SAME'-padded convs and
    input sizes divisible by 16 it is a no-op, exactly as in the reference.

The 2-stage pipeline split (reference unet_model.py:14-20: encoder+mid on
stage 0, decoder+head on stage 1) is NOT baked into the model here — stage
placement is a *strategy* concern handled in parallel/pipeline.py over the
same flax modules (`UNet.encode_mid` / `UNet.decode_head`).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributedpytorch_tpu.ops import s2d as s2d_ops

# Channel plan of the reference model (unet_parts.py:28-33, 16, 51-54).
ENCODER_WIDTHS = (32, 64, 128, 256)
MID_WIDTH = 512


def center_crop(x: jax.Array, target_hw: Tuple[int, int]) -> jax.Array:
    """Static center crop of an NHWC tensor to (H, W) = target_hw.

    Parity with torchvision CenterCrop as used at reference
    unet_parts.py:58-73. Shapes are static under jit, so this is a slice,
    not a dynamic gather.
    """
    h, w = x.shape[1], x.shape[2]
    th, tw = target_hw
    dh, dw = (h - th) // 2, (w - tw) // 2
    return x[:, dh : dh + th, dw : dw + tw, :]


class _S2DConv(nn.Module):
    """Param-compatible stand-in for ``nn.Conv``/``nn.ConvTranspose``
    evaluated in the space-to-depth domain (ops/s2d.py).

    Declares ``kernel``/``bias`` with the exact names, shapes, and
    initializers flax's own modules use, so checkpoints, the 7,760,097-param
    golden, and `.pth` interop are identical whether or not the s2d
    execution mode is on. The structured dense kernel is assembled from
    those parameters inside the traced computation — autodiff puts the
    gradients back on the original weights.

    Modes: ``conv3x3`` (s2d in → s2d out), ``upconv`` (pixel in → s2d out,
    the k=2 s=2 ConvTranspose), ``head`` (s2d in → s2d out, 1×1 conv).
    """

    features: int
    in_features: int
    mode: str = "conv3x3"
    dtype: Any = jnp.bfloat16
    in_segments: Optional[Tuple[int, ...]] = None
    # Route the 3x3 weight gradient through the 9-tap-matmul backward
    # (ops/conv_backward.py) instead of XLA's conv-backward-filter.
    wgrad_taps: bool = False
    # False for BatchNorm-following convs (milesial DoubleConv) — the
    # param tree then matches nn.Conv(use_bias=False) exactly.
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kshape = {"conv3x3": (3, 3), "upconv": (2, 2), "head": (1, 1)}[self.mode]
        w = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (*kshape, self.in_features, self.features),
            jnp.float32,
        )
        w = w.astype(self.dtype)
        x = x.astype(self.dtype)
        if self.mode == "conv3x3":
            dense = s2d_ops.conv3x3_kernel(w, self.in_segments)
        elif self.mode == "upconv":
            dense = s2d_ops.upconv_kernel(w)
        else:
            dense = s2d_ops.head1x1_kernel(w, self.in_segments)
        if self.wgrad_taps and self.mode == "conv3x3":
            from distributedpytorch_tpu.ops.conv_backward import (
                conv3x3_same_taps,
            )

            y = conv3x3_same_taps(x, dense)
        else:
            y = s2d_ops.conv_same(x, dense)
        if not self.use_bias:
            return y
        b = self.param(
            "bias", nn.initializers.zeros_init(), (self.features,), jnp.float32
        )
        return y + s2d_ops.tile_bias(b).astype(y.dtype)


class _TapsPixelConv(nn.Module):
    """Param-compatible stand-in for ``nn.Conv(features, (3,3), padding=1)``
    whose weight gradient runs through the 9-tap-matmul backward
    (ops/conv_backward.py). For a 3×3 stride-1 conv, flax's ``padding=1``
    IS 'SAME', so forward numerics are identical; only the backward
    schedule differs."""

    features: int
    dtype: Any = jnp.bfloat16
    use_bias: bool = True  # False matches nn.Conv(use_bias=False) (BN convs)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from distributedpytorch_tpu.ops.conv_backward import conv3x3_same_taps

        w = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (3, 3, x.shape[-1], self.features),
            jnp.float32,
        )
        y = conv3x3_same_taps(x.astype(self.dtype), w.astype(self.dtype))
        if not self.use_bias:
            return y
        b = self.param(
            "bias", nn.initializers.zeros_init(), (self.features,), jnp.float32
        )
        return y + b.astype(y.dtype)


class ConvBlock(nn.Module):
    """[Conv3×3(pad=1) → ReLU] × 2 (reference unet_parts.py:6-17).

    ``s2d=True`` evaluates both convs in the space-to-depth domain
    (ops/s2d.py) — exactly equivalent, ~2× faster on the shallow
    full-resolution levels where C ≪ the 128 MXU lanes. ``in_features`` /
    ``in_segments`` describe the logical input channels then (the s2d input
    tensor carries 4× that).
    """

    features: int
    dtype: Any = jnp.bfloat16
    s2d: bool = False
    in_features: Optional[int] = None
    in_segments: Optional[Tuple[int, ...]] = None
    wgrad_taps: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.s2d:
            assert self.in_features is not None
            x = _S2DConv(
                self.features,
                self.in_features,
                "conv3x3",
                dtype=self.dtype,
                in_segments=self.in_segments,
                wgrad_taps=self.wgrad_taps,
                name="conv1",
            )(x)
            x = nn.relu(x)
            x = _S2DConv(
                self.features, self.features, "conv3x3", dtype=self.dtype,
                wgrad_taps=self.wgrad_taps, name="conv2"
            )(x)
            x = nn.relu(x)
            return x
        conv = (
            functools.partial(_TapsPixelConv, dtype=self.dtype)
            if self.wgrad_taps
            else functools.partial(
                nn.Conv, kernel_size=(3, 3), padding=1, dtype=self.dtype
            )
        )
        x = conv(self.features, name="conv1")(x)
        x = nn.relu(x)
        x = conv(self.features, name="conv2")(x)
        x = nn.relu(x)
        return x


def _maxpool2x2(x: jax.Array) -> jax.Array:
    """MaxPool2d(kernel=2, stride=2) (reference unet_parts.py:26)."""
    return nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))


class Encoder(nn.Module):
    """4 conv_blocks with 2×2 maxpool between; returns bottleneck + 4 skips
    (reference unet_parts.py:19-41).

    The first ``s2d_levels`` levels run in the space-to-depth domain: their
    skip tensors are emitted in s2d form (the decoder consumes them there
    directly), and the 2×2 maxpool collapses to a max over the s2d group —
    its output is already the next level's pixel-resolution input.

    Levels are individually callable (`level`) so the S-stage pipeline can
    cut the model anywhere in its linear block order (parallel/pipeline.py);
    `__call__` chains them and is unchanged in numerics and param naming.
    """

    widths: Sequence[int] = ENCODER_WIDTHS
    dtype: Any = jnp.bfloat16
    s2d_levels: int = 0
    in_features: int = 3  # input channels (RGB images)
    wgrad_taps: bool = False

    def setup(self):
        blocks = []
        in_feats = self.in_features
        for i, w in enumerate(self.widths):
            if i < self.s2d_levels:
                blocks.append(ConvBlock(
                    w,
                    dtype=self.dtype,
                    s2d=True,
                    in_features=in_feats,
                    wgrad_taps=self.wgrad_taps,
                    name=f"block{i + 1}",
                ))
            else:
                blocks.append(ConvBlock(
                    w, dtype=self.dtype, wgrad_taps=self.wgrad_taps,
                    name=f"block{i + 1}",
                ))
            in_feats = w
        self.blocks = blocks

    def level(self, x: jax.Array, i: int) -> Tuple[jax.Array, jax.Array]:
        """Encoder level ``i``: conv block + pool → (pooled, skip)."""
        if i < self.s2d_levels:
            xs = s2d_ops.space_to_depth(x)
            xs = self.blocks[i](xs)
            return s2d_ops.group_max(xs), xs  # skip stays in s2d form
        x = self.blocks[i](x)
        return _maxpool2x2(x), x

    def __call__(self, x: jax.Array) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
        skips = []
        for i in range(len(self.widths)):
            x, skip = self.level(x, i)
            skips.append(skip)
        return x, tuple(skips)


class Decoder(nn.Module):
    """4 × [ConvTranspose(k=2,s=2) → center-crop skip → concat → conv_block]
    (reference unet_parts.py:43-77)."""

    widths: Sequence[int] = tuple(reversed(ENCODER_WIDTHS))  # 256,128,64,32
    dtype: Any = jnp.bfloat16
    s2d_levels: int = 0
    in_features: Optional[int] = None  # bottleneck channels (default 2·widths[0])
    wgrad_taps: bool = False

    def setup(self):
        # The shallowest s2d_levels iterations (i ≥ n − s2d_levels) run in
        # the s2d domain: the upconv becomes a 1×1 conv from the pixel-space
        # input, the skip arrives already in s2d form, and the concat needs
        # no data movement (the conv kernel's in_segments absorb the layout).
        n = len(self.widths)
        first_in = self.in_features or 2 * self.widths[0]
        ups, blocks = [], []
        for i, w in enumerate(self.widths):
            logical_in = first_in if i == 0 else self.widths[i - 1]
            if i >= n - self.s2d_levels:
                ups.append(_S2DConv(
                    w, logical_in, "upconv", dtype=self.dtype,
                    name=f"upconv{i + 1}",
                ))
                blocks.append(ConvBlock(
                    w,
                    dtype=self.dtype,
                    s2d=True,
                    in_features=2 * w,
                    in_segments=(w, w),
                    wgrad_taps=self.wgrad_taps,
                    name=f"block{i + 1}",
                ))
            else:
                ups.append(nn.ConvTranspose(
                    w, (2, 2), strides=(2, 2), dtype=self.dtype,
                    name=f"upconv{i + 1}",
                ))
                blocks.append(ConvBlock(
                    w, dtype=self.dtype, wgrad_taps=self.wgrad_taps,
                    name=f"block{i + 1}",
                ))
        self.ups = ups
        self.blocks = blocks

    def level(self, x: jax.Array, skip: jax.Array, i: int) -> jax.Array:
        """Decoder level ``i``: upconv → crop/concat skip → conv block.

        ``x`` arrives in s2d form iff level ``i−1`` ran in the s2d domain —
        a static property of ``i``, so pipeline stages can start at any
        level without threading execution-domain state across stages."""
        n = len(self.widths)
        if i >= n - self.s2d_levels:
            if i - 1 >= n - self.s2d_levels:
                x = s2d_ops.depth_to_space(x)
            up = self.ups[i](x)
            assert skip.shape == up.shape, (
                "s2d decoder expects the identity center-crop (even input "
                f"sizes): skip {skip.shape} vs upconv {up.shape}"
            )
            x = jnp.concatenate([skip, up], axis=-1)
            return self.blocks[i](x)
        x = self.ups[i](x)
        skip = center_crop(skip, (x.shape[1], x.shape[2]))
        x = jnp.concatenate([skip, x], axis=-1)
        return self.blocks[i](x)

    def __call__(self, x: jax.Array, skips: Sequence[jax.Array]) -> jax.Array:
        # skips arrive encoder-ordered (shallow→deep); consume deepest first.
        for i in range(len(self.widths)):
            x = self.level(x, skips[len(skips) - 1 - i], i)
        return x


class UNet(nn.Module):
    """Full UNet: Encoder → mid ConvBlock → Decoder → 1×1 head → sigmoid
    (reference model/unet_model.py:4-11, forward at :55-61).

    Input:  NHWC float, (B, H, W, 3), H and W divisible by 2**len(widths).
    Output: (B, H, W, 1) probabilities in (0, 1).

    `widths` defaults to the reference's channel plan (7,760,097 params);
    narrower/shallower variants (e.g. ``widths=(8, 16)``) compile in a
    fraction of the time — the test suite uses them for the parallelism
    machinery, where the model is a payload, not the thing under test.
    """

    n_classes: int = 1
    dtype: Any = jnp.bfloat16
    widths: Sequence[int] = ENCODER_WIDTHS
    mid_width: int = 0  # 0 = 2 × widths[-1] (the reference's 256→512)
    # Input channels. Static (setup-time) because the s2d execution mode
    # builds its level-1 kernels from it; the data pipeline always emits
    # RGB, so non-3 is for library users feeding other imagery.
    in_channels: int = 3
    # 9-tap-matmul weight gradients for the s2d 3x3 convs
    # (ops/conv_backward.py); measured A/B on TPU before defaulting.
    wgrad_taps: bool = False
    # How many shallow levels execute in the space-to-depth domain
    # (ops/s2d.py) — exactly equivalent, measured ~2× faster on TPU for the
    # full-resolution C=32/64 levels. 0 disables; -1 = auto (2 on a TPU
    # backend, 0 elsewhere — the 4× nominal MACs only pay off on the MXU).
    s2d_levels: int = -1

    def _s2d_levels(self) -> int:
        lv = self.s2d_levels
        if lv < 0:
            lv = 2 if jax.default_backend() == "tpu" else 0
        return max(0, min(lv, len(self.widths)))

    def setup(self):
        mid = self.mid_width or 2 * self.widths[-1]
        lv = self._s2d_levels()
        self.encoder = Encoder(
            widths=tuple(self.widths),
            dtype=self.dtype,
            s2d_levels=lv,
            in_features=self.in_channels,
            wgrad_taps=self.wgrad_taps,
        )
        self.mid = ConvBlock(
            mid, dtype=self.dtype, wgrad_taps=self.wgrad_taps
        )
        self.decoder = Decoder(
            widths=tuple(reversed(self.widths)),
            dtype=self.dtype,
            s2d_levels=lv,
            in_features=mid,
            wgrad_taps=self.wgrad_taps,
        )
        if lv > 0:
            self.segmap = _S2DConv(
                self.n_classes, self.widths[0], "head", dtype=self.dtype
            )
        else:
            self.segmap = nn.Conv(self.n_classes, (1, 1), dtype=self.dtype)

    def __call__(self, x: jax.Array) -> jax.Array:
        x, skips = self.encode_mid(x)
        return self.decode_head(x, skips)

    # -- pipeline stage boundaries (reference unet_model.py:16-20 cut) -----
    def _check_s2d_size(self, x: jax.Array) -> None:
        """The pixel path degrades gracefully on ragged sizes via the
        decoder's center-crop; the s2d path cannot — fail fast with the
        workaround instead of asserting deep in the first step. Called at
        every model entry (full forward, 2-stage cut, segment 0)."""
        if self._s2d_levels() > 0:
            div = 2 ** len(self.widths)
            h, w = x.shape[1], x.shape[2]
            if h % div or w % div:
                raise ValueError(
                    f"input {h}×{w} is not divisible by {div} "
                    f"(2**levels), which the space-to-depth execution mode "
                    f"requires — resize the input or pass s2d_levels=0 "
                    f"(CLI: --s2d-levels 0)"
                )

    def encode_mid(self, x: jax.Array) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
        """Stage 0 of the 2-stage pipeline: encoder + mid block."""
        self._check_s2d_size(x)
        x, skips = self.encoder(x)
        x = self.mid(x)
        return x, skips

    def decode_head(self, x: jax.Array, skips: Sequence[jax.Array]) -> jax.Array:
        """Stage 1 of the 2-stage pipeline: decoder + 1×1 head + sigmoid.

        The sigmoid runs in float32: probabilities feed a log-based loss and
        bfloat16 resolution near 0/1 would poison it.
        """
        x = self.decoder(x, skips)
        return self._head(x)

    def _head(self, x: jax.Array) -> jax.Array:
        from distributedpytorch_tpu.ops.precision import LOSS_DTYPE

        x = self.segmap(x)
        if self._s2d_levels() > 0:
            x = s2d_ops.depth_to_space(x)  # (B, H/2, W/2, 4·ncls) → (B, H, W, ncls)
        # sigmoid in the loss dtype: probabilities feed a log-based loss
        # and bf16 resolution near 0/1 would poison it (the policy's
        # LOSS_DTYPE contract — every --dtype keeps this boundary f32)
        return jax.nn.sigmoid(x.astype(LOSS_DTYPE))

    # -- S-stage pipeline segments (parallel/pipeline.py) -------------------
    # The model's linear block order: L encoder levels, the mid block, then
    # L decoder levels with the 1×1 head folded into the last. A pipeline
    # stage is any contiguous run of these 2L+1 segments; the reference's
    # 2-stage cut (unet_model.py:16-20) is the boundary after segment L.
    @property
    def num_segments(self) -> int:
        return 2 * len(self.widths) + 1

    def apply_segment(
        self, x: jax.Array, skips: Tuple[jax.Array, ...], seg: int,
        train: bool = False,
    ) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
        """Run segment ``seg`` (static int) of the linear block order.

        Carry convention: ``(x, skips)`` where ``skips`` holds the encoder
        outputs produced so far and not yet consumed — segments push during
        encode, pop (deepest-first) during decode, so the inter-stage
        payload at any cut is exactly this carry.

        ``train`` is the uniform segment signature shared with the
        stateful family (models/milesial.py `apply_segment`, where it
        selects batch-vs-running statistics); this model is stateless, so
        it is accepted and ignored.
        """
        L = len(self.widths)
        if seg == 0:
            self._check_s2d_size(x)
        if seg < L:  # encoder level
            x, skip = self.encoder.level(x, seg)
            return x, tuple(skips) + (skip,)
        if seg == L:  # mid block
            return self.mid(x), tuple(skips)
        i = seg - L - 1  # decoder level
        x = self.decoder.level(x, skips[-1], i)
        skips = tuple(skips)[:-1]
        if seg == 2 * L:  # last decoder level carries the head
            x = self._head(x)
        return x, skips


def create_unet(config=None, dtype=None) -> UNet:
    """Build a UNet from a TrainConfig (or dtype override)."""
    if dtype is None:
        from distributedpytorch_tpu.ops.precision import get_policy

        dtype = (
            get_policy(config).compute_dtype
            if config is not None
            else jnp.bfloat16
        )
    widths = ENCODER_WIDTHS
    if config is not None and getattr(config, "model_widths", None):
        widths = tuple(config.model_widths)
    s2d_levels = getattr(config, "s2d_levels", -1) if config is not None else -1
    wgrad_taps = getattr(config, "wgrad_taps", False) if config is not None else False
    return UNet(dtype=dtype, widths=widths, s2d_levels=s2d_levels,
                wgrad_taps=wgrad_taps)


def init_unet_params(model: UNet, rng: jax.Array, input_hw=(640, 960)):
    """Initialize parameters with a (1, H, W, 3) dummy batch."""
    dummy = jnp.zeros((1, input_hw[0], input_hw[1], 3), jnp.float32)
    return model.init(rng, dummy)["params"]


def param_count(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
