"""UNet for binary segmentation, TPU-native (flax.linen, NHWC).

Capability parity with the reference model (reference model/unet_parts.py:6-77,
model/unet_model.py:4-62): a 4-down/4-up UNet with channel widths
3→32→64→128→256, a 256→512 mid block, symmetric decoder with skip
concatenation, a 1×1 segmentation head, and a sigmoid output. Parameter-count
golden: 7,760,097 trainable parameters (reference model/modelsummary.txt:63).

TPU-first divergences from the reference (deliberate, not bugs):
  * NHWC layout throughout — XLA:TPU tiles the channel dimension onto the
    (8,128)/(16,128) vector lanes; NCHW would force relayouts around every
    conv. The data pipeline emits NHWC; a checkpoint shim handles NCHW
    interop (see checkpoint.py).
  * Convolutions are `flax.linen.Conv` → `lax.conv_general_dilated`; maxpool
    is `lax.reduce_window`; the 2×2-stride-2 up-convolution is
    `flax.linen.ConvTranspose` → `lax.conv_transpose`. All lower to MXU/VPU
    ops — no Python-level loops.
  * Compute dtype is configurable (default bfloat16 for the MXU); parameters
    are float32.
  * The center-crop of skip tensors (reference unet_parts.py:58-73 uses
    torchvision CenterCrop) is a static slice; with 'SAME'-padded convs and
    input sizes divisible by 16 it is a no-op, exactly as in the reference.

The 2-stage pipeline split (reference unet_model.py:14-20: encoder+mid on
stage 0, decoder+head on stage 1) is NOT baked into the model here — stage
placement is a *strategy* concern handled in parallel/pipeline.py over the
same flax modules (`UNet.encode_mid` / `UNet.decode_head`).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

# Channel plan of the reference model (unet_parts.py:28-33, 16, 51-54).
ENCODER_WIDTHS = (32, 64, 128, 256)
MID_WIDTH = 512


def center_crop(x: jax.Array, target_hw: Tuple[int, int]) -> jax.Array:
    """Static center crop of an NHWC tensor to (H, W) = target_hw.

    Parity with torchvision CenterCrop as used at reference
    unet_parts.py:58-73. Shapes are static under jit, so this is a slice,
    not a dynamic gather.
    """
    h, w = x.shape[1], x.shape[2]
    th, tw = target_hw
    dh, dw = (h - th) // 2, (w - tw) // 2
    return x[:, dh : dh + th, dw : dw + tw, :]


class ConvBlock(nn.Module):
    """[Conv3×3(pad=1) → ReLU] × 2 (reference unet_parts.py:6-17)."""

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Conv(self.features, (3, 3), padding=1, dtype=self.dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.Conv(self.features, (3, 3), padding=1, dtype=self.dtype, name="conv2")(x)
        x = nn.relu(x)
        return x


def _maxpool2x2(x: jax.Array) -> jax.Array:
    """MaxPool2d(kernel=2, stride=2) (reference unet_parts.py:26)."""
    return nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))


class Encoder(nn.Module):
    """4 conv_blocks with 2×2 maxpool between; returns bottleneck + 4 skips
    (reference unet_parts.py:19-41)."""

    widths: Sequence[int] = ENCODER_WIDTHS
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
        skips = []
        for i, w in enumerate(self.widths):
            x = ConvBlock(w, dtype=self.dtype, name=f"block{i + 1}")(x)
            skips.append(x)
            x = _maxpool2x2(x)
        return x, tuple(skips)


class Decoder(nn.Module):
    """4 × [ConvTranspose(k=2,s=2) → center-crop skip → concat → conv_block]
    (reference unet_parts.py:43-77)."""

    widths: Sequence[int] = tuple(reversed(ENCODER_WIDTHS))  # 256,128,64,32
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, skips: Sequence[jax.Array]) -> jax.Array:
        # skips arrive encoder-ordered (shallow→deep); consume deepest first.
        for i, (w, skip) in enumerate(zip(self.widths, reversed(skips))):
            x = nn.ConvTranspose(
                w, (2, 2), strides=(2, 2), dtype=self.dtype, name=f"upconv{i + 1}"
            )(x)
            skip = center_crop(skip, (x.shape[1], x.shape[2]))
            x = jnp.concatenate([skip, x], axis=-1)
            x = ConvBlock(w, dtype=self.dtype, name=f"block{i + 1}")(x)
        return x


class UNet(nn.Module):
    """Full UNet: Encoder → mid ConvBlock → Decoder → 1×1 head → sigmoid
    (reference model/unet_model.py:4-11, forward at :55-61).

    Input:  NHWC float, (B, H, W, 3), H and W divisible by 2**len(widths).
    Output: (B, H, W, 1) probabilities in (0, 1).

    `widths` defaults to the reference's channel plan (7,760,097 params);
    narrower/shallower variants (e.g. ``widths=(8, 16)``) compile in a
    fraction of the time — the test suite uses them for the parallelism
    machinery, where the model is a payload, not the thing under test.
    """

    n_classes: int = 1
    dtype: Any = jnp.bfloat16
    widths: Sequence[int] = ENCODER_WIDTHS
    mid_width: int = 0  # 0 = 2 × widths[-1] (the reference's 256→512)

    def setup(self):
        mid = self.mid_width or 2 * self.widths[-1]
        self.encoder = Encoder(widths=tuple(self.widths), dtype=self.dtype)
        self.mid = ConvBlock(mid, dtype=self.dtype)
        self.decoder = Decoder(
            widths=tuple(reversed(self.widths)), dtype=self.dtype
        )
        self.segmap = nn.Conv(self.n_classes, (1, 1), dtype=self.dtype)

    def __call__(self, x: jax.Array) -> jax.Array:
        x, skips = self.encode_mid(x)
        return self.decode_head(x, skips)

    # -- pipeline stage boundaries (reference unet_model.py:16-20 cut) -----
    def encode_mid(self, x: jax.Array) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
        """Stage 0 of the 2-stage pipeline: encoder + mid block."""
        x, skips = self.encoder(x)
        x = self.mid(x)
        return x, skips

    def decode_head(self, x: jax.Array, skips: Sequence[jax.Array]) -> jax.Array:
        """Stage 1 of the 2-stage pipeline: decoder + 1×1 head + sigmoid.

        The sigmoid runs in float32: probabilities feed a log-based loss and
        bfloat16 resolution near 0/1 would poison it.
        """
        x = self.decoder(x, skips)
        x = self.segmap(x)
        return jax.nn.sigmoid(x.astype(jnp.float32))


def create_unet(config=None, dtype=None) -> UNet:
    """Build a UNet from a TrainConfig (or dtype override)."""
    if dtype is None:
        dtype = jnp.dtype(config.compute_dtype) if config is not None else jnp.bfloat16
    widths = ENCODER_WIDTHS
    if config is not None and getattr(config, "model_widths", None):
        widths = tuple(config.model_widths)
    return UNet(dtype=dtype, widths=widths)


def init_unet_params(model: UNet, rng: jax.Array, input_hw=(640, 960)):
    """Initialize parameters with a (1, H, W, 3) dummy batch."""
    dummy = jnp.zeros((1, input_hw[0], input_hw[1], 3), jnp.float32)
    return model.init(rng, dummy)["params"]


def param_count(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
