"""``python -m distributedpytorch_tpu`` → the training CLI (same surface
as ``train.py`` / the ``dpt-train`` console script)."""

from distributedpytorch_tpu.cli import main

if __name__ == "__main__":
    main()
