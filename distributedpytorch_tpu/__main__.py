"""``python -m distributedpytorch_tpu`` → the training CLI (same surface
as ``train.py`` / the ``dpt-train`` console script), plus the elastic
supervisor subcommand:

    python -m distributedpytorch_tpu elastic -n 2 -- -t FSDP ...

which spawns/supervises the worker ranks (dist/elastic.py) the way the
reference's ``torchrun`` launcher does (README.md:37), and the static
analyzer:

    python -m distributedpytorch_tpu analyze [--strategies ...]

which runs dptlint (analysis/: jaxpr collective checker + SPMD source
lint; docs/ANALYSIS.md) on a self-provisioned CPU mesh — the CI
``lint-distributed`` gate and the bench/elastic preflights call this —
the parallelism auto-planner:

    python -m distributedpytorch_tpu plan --out plan.json

which searches strategy × schedule × memory levers with zero device
execution and emits a ranked plan file for ``bench_multi --plan``
(analysis/planner.py, docs/PERFORMANCE.md "Planning") — its serving
twin:

    python -m distributedpytorch_tpu plan-serve --profile profile.json

which replays arrival traces against profiled service times in a
discrete-event simulation of the live queue policy and emits replica
recommendations per (traffic, SLO) with zero devices and zero jax
(analysis/serve_planner.py, docs/SERVING.md "Capacity planning") — and
the serving tier:

    python -m distributedpytorch_tpu serve -c singleGPU --port 8008

AOT-compiled, continuous-batching inference over HTTP (serve/,
docs/SERVING.md) — the inference-side production workload — and its
executable store manager:

    python -m distributedpytorch_tpu aot {warm,ls,gc}

prewarm / inspect / LRU-bound the content-addressed AOT executable
store (utils/aotstore.py, docs/PERFORMANCE.md "AOT executable
store")."""

import sys


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "elastic":
        from distributedpytorch_tpu.dist.elastic import main as elastic_main

        sys.exit(elastic_main(sys.argv[2:]))
    if len(sys.argv) > 1 and sys.argv[1] == "analyze":
        from distributedpytorch_tpu.analysis.cli import main as analyze_main

        sys.exit(analyze_main(sys.argv[2:]))
    if len(sys.argv) > 1 and sys.argv[1] == "plan":
        from distributedpytorch_tpu.analysis.planner import main as plan_main

        sys.exit(plan_main(sys.argv[2:]))
    if len(sys.argv) > 1 and sys.argv[1] == "plan-serve":
        from distributedpytorch_tpu.analysis.serve_planner import (
            main as plan_serve_main,
        )

        sys.exit(plan_serve_main(sys.argv[2:]))
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        from distributedpytorch_tpu.serve.cli import main as serve_main

        sys.exit(serve_main(sys.argv[2:]))
    if len(sys.argv) > 1 and sys.argv[1] == "aot":
        from distributedpytorch_tpu.utils.aotstore import main as aot_main

        sys.exit(aot_main(sys.argv[2:]))
    from distributedpytorch_tpu.cli import main as cli_main

    cli_main()


if __name__ == "__main__":
    main()
